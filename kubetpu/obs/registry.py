"""Typed metric instruments + a thread-safe registry with Prometheus text
exposition — THE metrics surface for every kubetpu process (agent,
controller, serving replica), replacing the ad-hoc counter dicts the wire
servers grew and absorbing ``core.metrics.LatencyRecorder`` behind one API.

Design constraints, in order:

- **thread-safe, lock-cheap**: instruments are written from request
  threads (ThreadingHTTPServer handlers) and the serving host loop; each
  instrument carries its own small lock so a scrape never blocks a writer
  for longer than one value copy;
- **bounded memory**: histograms keep a fixed-size reservoir — exact
  percentiles below the cap, uniform reservoir sampling above it (every
  observation has equal probability cap/count of being retained, so
  quantile estimates stay unbiased); count and sum stay exact. A
  long-running controller cannot grow without bound no matter how many
  pods it schedules;
- **Prometheus text**: ``Registry.render()`` emits the text exposition
  format (``# TYPE`` per metric; histograms as summaries with
  ``quantile`` labels plus ``_count``/``_sum``). ``parse_prometheus_text``
  / ``validate_prometheus_text`` are the other half — what the controller
  uses to federate agent scrapes (``federate``) and what ``make
  obs-check`` uses to fail on malformed output;
- **label order is preserved** (not sorted): callers write labels in a
  stable order and the emitted series match byte-for-byte across scrapes,
  which keeps substring-pinning tests and text diffs honest.

Stdlib only; no other kubetpu imports.
"""

from __future__ import annotations

import random
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

# label values may contain anything except unescaped quotes/newlines;
# names follow the Prometheus grammar
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SERIES_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>[^\s]+)"
    r"(?:\s+(?P<ts>[0-9]+))?$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

_QUANTILES = (0.5, 0.9, 0.99)


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render bare (``1``, not ``1.0``)
    so counter lines stay byte-stable and greppable."""
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: backslash, quote AND newline —
    a raw newline inside a label would split the series line and corrupt
    the whole exposition."""
    return (v.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _unescape_label_value(s: str) -> str:
    """Inverse of ``_escape_label_value`` — a proper left-to-right scan
    (sequential ``str.replace`` calls mangle adjacent escapes: ``\\\\"``
    must decode to ``\\"``, not ``"``)."""
    out: List[str] = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt,
                                                             "\\" + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _fmt_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    body = ",".join(
        '%s="%s"' % (k, _escape_label_value(str(v))) for k, v in labels
    )
    return "{" + body + "}"


class Counter:
    """Monotonic counter. Name it ``*_total`` by convention."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value; ``fn`` makes it a collect-time callback gauge
    (evaluated at render, so scrape-cost state like queue depth needs no
    per-mutation bookkeeping)."""

    def __init__(self, fn: Optional[Callable[[], float]] = None) -> None:
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn = fn

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — a scrape must never 500
                return float("nan")
        with self._lock:
            return self._value


class Histogram:
    """Bounded-reservoir histogram reporting p50/p90/p99.

    Below ``cap`` observations the reservoir holds EVERY sample, so the
    percentiles are exact. Past the cap, uniform reservoir sampling
    (Vitter's algorithm R) keeps each of the ``count`` observations with
    equal probability ``cap/count`` — quantiles become unbiased estimates
    with error shrinking as cap grows. ``count`` and ``sum`` stay exact
    throughout. The RNG is seeded per-instrument so a fixed observation
    order replays bit-for-bit (chaos-test determinism discipline)."""

    def __init__(self, cap: int = 2048, seed: int = 0) -> None:
        if cap <= 0:
            raise ValueError("cap must be positive")
        self.cap = cap
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._buf: List[float] = []
        self._count = 0
        self._sum = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            if len(self._buf) < self.cap:
                self._buf.append(v)
            else:
                j = self._rng.randrange(self._count)
                if j < self.cap:
                    self._buf[j] = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, p: float) -> float:
        """p in [0, 100]; 0.0 with no samples (nearest-rank, matching the
        pre-obs ``LatencyRecorder`` convention so pinned numbers hold)."""
        with self._lock:
            buf = sorted(self._buf)
        if not buf:
            return 0.0
        idx = min(len(buf) - 1,
                  max(0, int(round(p / 100.0 * (len(buf) - 1)))))
        return buf[idx]

    def tail(self) -> Tuple[int, List[float]]:
        """(exact observation count, copy of the reservoir) under the
        lock — the SLO engine's windowed-percentile hook: below the cap
        the reservoir is an append-only log, so an index cursor into it
        delimits exactly the observations that arrived since the cursor
        was taken."""
        with self._lock:
            return self._count, list(self._buf)


class Registry:
    """Get-or-create instrument store, keyed by (name, labels).

    ``counter/gauge/histogram`` return the live instrument (creating it on
    first use); re-requesting the same (name, labels) with a different
    instrument type raises — one name, one type, like Prometheus.
    ``render()`` emits the whole registry as exposition text, grouped by
    metric name with one ``# TYPE`` line each, in first-registration
    order."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (name, labels) -> instrument; dict preserves insertion order
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], object] = {}
        self._types: Dict[str, str] = {}
        self._help: Dict[str, str] = {}

    def _get(self, kind: str, name: str, help_: str,
             labels: Dict[str, object], factory):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        key = (name, tuple((k, str(v)) for k, v in labels.items()))
        with self._lock:
            got = self._metrics.get(key)
            if got is not None:
                if self._types[name] != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{self._types[name]}, not {kind}"
                    )
                return got
            if name in self._types and self._types[name] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{self._types[name]}, not {kind}"
                )
            inst = factory()
            self._metrics[key] = inst
            self._types[name] = kind
            if help_:
                self._help[name] = help_
            return inst

    # -- instrument accessors ------------------------------------------------

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get("counter", name, help, labels, Counter)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get("gauge", name, help, labels, Gauge)

    def gauge_fn(self, name: str, fn: Callable[[], float],
                 help: str = "", **labels) -> Gauge:
        """Collect-time gauge: *fn* is evaluated at every render."""
        g = self._get("gauge", name, help, labels, lambda: Gauge(fn=fn))
        return g

    def histogram(self, name: str, help: str = "", cap: int = 2048,
                  **labels) -> Histogram:
        return self._get("summary", name, help, labels,
                         lambda: Histogram(cap=cap))

    def attach_histogram(self, name: str, hist: Histogram,
                         help: str = "", **labels) -> Histogram:
        """Register an EXISTING histogram under this registry (how
        ``LatencyRecorder.bind`` exports per-op histograms it already
        holds without copying samples)."""
        return self._get("summary", name, help, labels, lambda: hist)

    # -- exposition ----------------------------------------------------------

    def snapshot(self):
        """[(name, labels, kind, instrument)] in registration order."""
        with self._lock:
            items = list(self._metrics.items())
            types = dict(self._types)
        return [(name, labels, types[name], inst)
                for (name, labels), inst in items]

    def render(self) -> str:
        """Prometheus text exposition of every instrument."""
        lines: List[str] = []
        typed: set = set()
        for name, labels, kind, inst in self.snapshot():
            if name not in typed:
                typed.add(name)
                if name in self._help:
                    lines.append(f"# HELP {name} {self._help[name]}")
                lines.append(f"# TYPE {name} {kind}")
            if kind in ("counter", "gauge"):
                lines.append(f"{name}{_fmt_labels(labels)} {_fmt(inst.value)}")
            else:  # summary
                for q in _QUANTILES:
                    ql = labels + (("quantile", _fmt(q)),)
                    lines.append(
                        f"{name}{_fmt_labels(ql)} "
                        f"{_fmt(inst.percentile(q * 100.0))}"
                    )
                lines.append(
                    f"{name}_count{_fmt_labels(labels)} {_fmt(inst.count)}")
                lines.append(
                    f"{name}_sum{_fmt_labels(labels)} {_fmt(inst.sum)}")
        return "\n".join(lines) + "\n" if lines else ""


# -- standard process gauges --------------------------------------------------

# process start proxy: first kubetpu.obs import (the true start isn't
# portably readable; for the uptime gauge's purpose — "how long has this
# replica been up" on a federated dashboard — import time is the honest
# approximation, since every kubetpu process imports obs at boot)
_PROC_START = time.time()


def _build_version() -> str:
    """The version stamped into ``kubetpu_build_info`` — the installed
    distribution's, falling back to the in-tree package constant (the
    usual case for a checked-out repo), then a sentinel."""
    try:
        from importlib.metadata import version

        return version("kubetpu")
    except Exception:  # noqa: BLE001 — not installed as a distribution
        pass
    import sys

    mod = sys.modules.get("kubetpu")
    return getattr(mod, "__version__", None) or "0+unknown"


def _rss_bytes() -> float:
    """Resident set size via stdlib ``resource`` (the satellite's
    contract): ``ru_maxrss`` is kilobytes on Linux, bytes on macOS.
    Peak-RSS, not instantaneous — good enough to spot a leaking replica
    on a dashboard, with zero dependencies."""
    import sys

    try:
        import resource

        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return float(rss if sys.platform == "darwin" else rss * 1024)
    except Exception:  # noqa: BLE001 — non-unix: a scrape must never 500
        return float("nan")


def install_process_gauges(registry: Registry, component: str,
                           version: Optional[str] = None) -> None:
    """The standard identification trio every kubetpu registry carries
    (agent, controller, serving replica): ``kubetpu_build_info{version,
    component} 1`` (the Prometheus build-info idiom — the VALUE is
    constant, the labels are the payload), process uptime seconds, and
    RSS bytes. Federated scrapes then identify every replica (version
    skew, restart storms, memory creep) without out-of-band
    bookkeeping. Idempotent per registry."""
    registry.gauge("kubetpu_build_info",
                   version=version or _build_version(),
                   component=component).set(1)
    registry.gauge_fn("kubetpu_process_uptime_seconds",
                      lambda: time.time() - _PROC_START)
    registry.gauge_fn("kubetpu_process_rss_bytes", _rss_bytes)


# -- process-default registry ------------------------------------------------

_DEFAULT = Registry()


def default_registry() -> Registry:
    """The process-wide registry. The wire CLIENT metrics
    (``kubetpu_wire_requests_total`` / ``_retried_total``) land here, so a
    process that is purely a client (gang_launch, schedsim) still has a
    registry to expose or assert on. Servers create their OWN registries —
    in-process test stacks (controller + N agents in one interpreter) must
    not share counters or federation would double-count."""
    return _DEFAULT


# -- parsing / validation / federation ---------------------------------------


def parse_prometheus_text(text: str):
    """[(name, labels dict, value)] for every sample line; comments and
    blanks skipped. Raises ``ValueError`` on a malformed line — callers
    that must not fail (the federating controller) catch and skip."""
    out = []
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = _SERIES_RE.match(line)
        if m is None:
            raise ValueError(f"malformed series line {lineno}: {raw!r}")
        labels: Dict[str, str] = {}
        body = m.group("labels")
        if body:
            # lenient here (strict grammar checks live in validate): pull
            # every well-formed pair, unescape
            for lm in _LABEL_RE.finditer(body):
                labels[lm.group(1)] = _unescape_label_value(lm.group(2))
        try:
            value = float(m.group("value"))
        except ValueError as e:
            raise ValueError(
                f"bad sample value on line {lineno}: {raw!r}") from e
        out.append((m.group("name"), labels, value))
    return out


def validate_prometheus_text(text: str) -> List[str]:
    """Problems found in *text* as Prometheus exposition (empty = valid):
    malformed lines, unknown TYPE declarations, duplicate series, samples
    under a declared summary/histogram name missing their suffix
    grammar. The ``make obs-check`` oracle."""
    problems: List[str] = []
    seen: set = set()
    known_types = {"counter", "gauge", "summary", "histogram", "untyped"}
    declared: Dict[str, str] = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip("\n")
        if not line.strip():
            continue
        if line.startswith("# TYPE"):
            parts = line.split()
            if len(parts) != 4 or not _NAME_RE.match(parts[2]):
                problems.append(f"line {lineno}: malformed TYPE line {raw!r}")
            elif parts[3] not in known_types:
                problems.append(
                    f"line {lineno}: unknown metric type {parts[3]!r}")
            elif parts[2] in declared:
                problems.append(
                    f"line {lineno}: duplicate TYPE for {parts[2]!r}")
            else:
                declared[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SERIES_RE.match(line.strip())
        if m is None:
            problems.append(f"line {lineno}: malformed series line {raw!r}")
            continue
        try:
            float(m.group("value"))
        except ValueError:
            problems.append(f"line {lineno}: bad value in {raw!r}")
        body = m.group("labels")
        labels: Tuple = ()
        if body is not None:
            pairs = _LABEL_RE.findall(body)
            rebuilt = ",".join('%s="%s"' % (k, v) for k, v in pairs)
            if rebuilt != body.rstrip(","):
                problems.append(
                    f"line {lineno}: malformed label set {{{body}}}")
            labels = tuple(pairs)
        key = (m.group("name"), labels)
        if key in seen:
            problems.append(
                f"line {lineno}: duplicate series {m.group('name')}"
                f"{_fmt_labels(labels)}")
        seen.add(key)
    return problems


def _series_lines(text: str, extra_label: Tuple[str, str]):
    """(name -> type) and relabeled sample lines of *text* with
    *extra_label* appended to every series that doesn't already carry that
    label key (agent capacity series already carry ``node=``)."""
    types: Dict[str, str] = {}
    for line in text.splitlines():
        if line.startswith("# TYPE"):
            parts = line.split()
            if len(parts) == 4:
                types[parts[2]] = parts[3]
    lines: List[str] = []
    for name, labels, value in parse_prometheus_text(text):
        if extra_label[0] not in labels:
            labels = dict(labels)
            labels[extra_label[0]] = extra_label[1]
        lt = tuple((k, v) for k, v in labels.items())
        lines.append(f"{name}{_fmt_labels(lt)} {_fmt(value)}")
    return types, lines


def federate(own: str, scraped: Dict[str, str], label: str = "node") -> str:
    """Merge this process's exposition *own* with *scraped* peer
    expositions ({peer name -> text}), relabeling every peer series with
    ``<label>="<name>"`` — the controller's fleet ``/metrics`` (label
    ``node``) and the exporter's multi-registry merge (``component``).
    Peer ``TYPE`` lines are deduplicated against the local ones; a peer
    text that fails to parse is skipped wholesale (federation must
    degrade, never 500)."""
    out_lines = own.rstrip("\n").splitlines() if own.strip() else []
    typed = {ln.split()[2] for ln in out_lines if ln.startswith("# TYPE")}
    for node in sorted(scraped):
        try:
            types, lines = _series_lines(scraped[node], (label, node))
        except ValueError:
            continue
        for name, kind in types.items():
            if name not in typed:
                typed.add(name)
                out_lines.append(f"# TYPE {name} {kind}")
        out_lines.extend(lines)
    return "\n".join(out_lines) + "\n" if out_lines else ""
