"""``kubetpu.obs`` — the one observability spine (Round-8).

SURVEY.md §5.1 records that the reference has no tracing or profiling
hooks at all, and the BASELINE north star (pod-schedule p50 < 100 ms at
256 chips; serving TTFT/ITL targets) is unmeasurable in production
without them. Before this subsystem kubetpu's observability was four
disconnected fragments — the scheduler's ``LatencyRecorder``, the agent's
ad-hoc ``/metrics`` counter dict, serving's in-process
``metrics_summary()``, and the jobs-side ``profiling`` helpers. ``obs``
is the spine they all hang off:

- ``registry`` — typed instruments (Counter, Gauge, bounded-reservoir
  Histogram with p50/p90/p99) in a thread-safe ``Registry`` with
  Prometheus text exposition, plus the parse/validate/federate helpers
  the controller uses to merge agent scrapes into one fleet ``/metrics``;
- ``trace`` — lightweight distributed tracing: ``span()`` produces
  structured events (trace_id/span_id/parent, op, start, dur, tags) into
  a bounded process-wide ``Tracer`` (optional JSONL sink), and the wire
  layer propagates the context via ``X-Kubetpu-Trace-Id`` /
  ``X-Kubetpu-Parent-Span`` headers so one ``gang_launch`` or pod submit
  yields a single stitched trace across controller -> agent -> allocate
  (retries visible as child spans);
- ``exporter`` — a tiny stdlib HTTP server exposing any ``Registry`` (and
  the process tracer) as ``/metrics`` + ``/trace/<id>`` + ``/events``,
  the wire path a serving replica (DecodeServer and friends) publishes
  its histograms through.

Round-11 adds the SIGNAL layer on top of the recording spine — the
judge-and-explain surface the autoscaling roadmap item runs on:

- ``slo`` — declarative objectives (TTFT p95, ITL p99, queue-wait p99,
  availability, pool-free-pages floor) evaluated over Registry
  snapshots / federated scrapes with fast/slow multi-window burn rates,
  rendered as ``kubetpu_slo_*`` gauges;
- ``profile`` — a sampled, off-by-default continuous profiler for the
  slot servers: per-phase step breakdown plus jit-recompile counters
  (count + compile seconds per leg), zero cost while disabled;
- ``events`` — a bounded structured event log (admission, retire,
  prefix-cache hit/evict, breaker transitions, gamma changes, drain,
  checkpoint) with JSONL sink and ``GET /events``, cross-linked to
  trace ids.

Deliberately dependency-free (stdlib only) and import-light: every other
layer (wire, core, scheduler, jobs) may import ``obs``; ``obs`` imports
none of them.
"""

from kubetpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    default_registry,
    federate,
    install_process_gauges,
    parse_prometheus_text,
    validate_prometheus_text,
)
from kubetpu.obs.trace import (
    Tracer,
    attach_wire_context,
    current_span_id,
    current_trace_id,
    span,
    tracer,
    wire_headers,
)
from kubetpu.obs.events import (
    EventLog,
    event_log,
    merge_events,
    validate_events_jsonl,
)
from kubetpu.obs.slo import (
    Objective,
    SloEngine,
    disagg_slos,
    fleet_slos,
    router_slos,
    serving_slos,
)
from kubetpu.obs.profile import ServingProfiler

__all__ = [
    "Counter",
    "EventLog",
    "Gauge",
    "Histogram",
    "Objective",
    "Registry",
    "ServingProfiler",
    "SloEngine",
    "Tracer",
    "attach_wire_context",
    "current_span_id",
    "current_trace_id",
    "default_registry",
    "disagg_slos",
    "event_log",
    "federate",
    "fleet_slos",
    "router_slos",
    "install_process_gauges",
    "merge_events",
    "parse_prometheus_text",
    "serving_slos",
    "span",
    "tracer",
    "validate_events_jsonl",
    "validate_prometheus_text",
    "wire_headers",
]
