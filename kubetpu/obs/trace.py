"""Lightweight distributed tracing for the kubetpu wire + workload stack.

One ``span()`` context manager produces structured events — trace_id /
span_id / parent, op, component, start (epoch seconds), dur, tags —
recorded into a bounded process-wide ``Tracer`` ring (optionally teed to a
JSONL sink for offline inspection). Context rides a ``contextvars``
ContextVar, so nested spans parent correctly per thread, and crosses the
process boundary as two HTTP headers:

    X-Kubetpu-Trace-Id:    32-hex trace id
    X-Kubetpu-Parent-Span: 16-hex span id of the caller's span

``httpcommon.request_json`` injects them per attempt (so a retry's child
span becomes the server span's parent — retries are VISIBLE in the
stitched trace), and ``handle_guarded`` extracts them before routing, so
one ``gang_launch`` or pod submit yields a single trace spanning
controller -> agent -> allocate.

Sampling: everything is recorded; the ring bounds memory (dropped-oldest,
``dropped`` counter keeps the loss honest). The hot wire paths produce a
handful of spans per request — cheap next to one HTTP exchange. Code that
would span per (pod x node) in the scheduler predicate loop must NOT: the
discipline is spans at operation granularity (schedule, allocate, probe),
histograms at loop granularity.

Env: ``KUBETPU_TRACE_SINK=/path/f.jsonl`` opens the sink at import.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional, Tuple

TRACE_HEADER = "X-Kubetpu-Trace-Id"
PARENT_HEADER = "X-Kubetpu-Parent-Span"

# (trace_id, span_id) of the currently-executing span in this context
_ctx: contextvars.ContextVar[Optional[Tuple[str, str]]] = contextvars.ContextVar(
    "kubetpu_trace_ctx", default=None
)


def _new_trace_id() -> str:
    return uuid.uuid4().hex


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    """One in-flight span; finished spans are stored as plain dicts."""

    __slots__ = ("trace_id", "span_id", "parent_id", "op", "component",
                 "start", "tags", "status", "_t0")

    def __init__(self, trace_id: str, span_id: str, parent_id: Optional[str],
                 op: str, component: Optional[str], tags: Dict) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.op = op
        self.component = component
        self.start = time.time()
        self.tags = dict(tags)
        self.status = "ok"
        self._t0 = time.perf_counter()

    def tag(self, **kv) -> "Span":
        self.tags.update(kv)
        return self

    def _finish(self) -> dict:
        out = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "op": self.op,
            "start": self.start,
            "dur": time.perf_counter() - self._t0,
            "status": self.status,
        }
        if self.component:
            out["component"] = self.component
        if self.tags:
            # tags must be JSON-serializable for the sink; coerce defensively
            out["tags"] = {k: v if isinstance(v, (str, int, float, bool,
                                                  type(None))) else str(v)
                           for k, v in self.tags.items()}
        return out


class Tracer:
    """Bounded ring of finished spans + optional JSONL sink.

    The process-wide instance (``tracer()``) is what the wire servers
    serve at ``GET /trace/<id>``; tests may instantiate their own and pass
    it to ``span(tracer_=...)`` for isolation."""

    def __init__(self, capacity: int = 8192) -> None:
        self._lock = threading.Lock()
        # the sink has its OWN lock: disk I/O must never hold up the ring
        # (every request thread records spans; only the sink writer pays
        # the filesystem)
        self._sink_lock = threading.Lock()
        self._spans: deque = deque(maxlen=capacity)
        self.dropped = 0
        self._sink_path: Optional[str] = None
        self._sink = None

    def record(self, span_dict: dict) -> None:
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append(span_dict)
            sink = self._sink
        if sink is not None:
            line = json.dumps(span_dict) + "\n"
            with self._sink_lock:
                if self._sink is not sink:  # closed/replaced concurrently
                    return
                try:
                    sink.write(line)
                    sink.flush()
                except OSError:
                    # a full/unwritable sink must never take the workload
                    # down; the ring keeps recording
                    self._sink = None

    def spans(self, trace_id: Optional[str] = None) -> List[dict]:
        """Finished spans (oldest first), optionally for one trace."""
        with self._lock:
            out = list(self._spans)
        if trace_id is not None:
            out = [s for s in out if s["trace_id"] == trace_id]
        return out

    def set_sink(self, path: Optional[str]) -> None:
        """Tee every finished span to *path* as one JSON line (append);
        None closes the sink."""
        new_sink = open(path, "a", encoding="utf-8") if path else None
        with self._sink_lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except OSError:
                    pass
            self._sink_path = path
            self._sink = new_sink  # attribute swap is atomic under the GIL

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0


_TRACER = Tracer()
if os.environ.get("KUBETPU_TRACE_SINK"):
    try:
        _TRACER.set_sink(os.environ["KUBETPU_TRACE_SINK"])
    except OSError:
        pass


def tracer() -> Tracer:
    """The process-wide tracer."""
    return _TRACER


# -- context accessors --------------------------------------------------------


def current_trace_id() -> Optional[str]:
    cur = _ctx.get()
    return cur[0] if cur else None


def current_span_id() -> Optional[str]:
    cur = _ctx.get()
    return cur[1] if cur else None


def wire_headers() -> Dict[str, str]:
    """Headers that carry the CURRENT span context to a server; empty when
    no span is active (the callee then starts its own trace)."""
    cur = _ctx.get()
    if cur is None:
        return {}
    out = {TRACE_HEADER: cur[0]}
    if cur[1]:
        out[PARENT_HEADER] = cur[1]
    return out


@contextlib.contextmanager
def attach_wire_context(headers):
    """Adopt an INCOMING request's trace context (server side) for the
    duration: spans opened inside parent under the remote caller's span.
    *headers* is any mapping with ``.get`` (http.server's message object).
    No-op when the request carries no trace headers."""
    tid = headers.get(TRACE_HEADER) if headers is not None else None
    if not tid:
        yield
        return
    # a missing parent header still adopts the trace id: spans become
    # additional ROOTS of the same trace rather than children of a
    # phantom span id
    parent = headers.get(PARENT_HEADER) or None
    token = _ctx.set((tid, parent))
    try:
        yield
    finally:
        _ctx.reset(token)


@contextlib.contextmanager
def span(op: str, component: Optional[str] = None,
         tracer_: Optional[Tracer] = None, **tags):
    """Open a span: child of the current context's span, or a fresh trace
    root when none is active. Yields the ``Span`` (mutate via ``.tag()``);
    an exception marks ``status="error"`` with the message tagged, records
    the span, and re-raises."""
    parent = _ctx.get()
    if parent is None:
        trace_id, parent_id = _new_trace_id(), None
    else:
        trace_id, parent_id = parent
    sp = Span(trace_id, _new_span_id(), parent_id, op, component, tags)
    token = _ctx.set((trace_id, sp.span_id))
    try:
        yield sp
    except BaseException as e:
        sp.status = "error"
        sp.tags.setdefault("error", f"{type(e).__name__}: {e}")
        raise
    finally:
        _ctx.reset(token)
        (tracer_ or _TRACER).record(sp._finish())
