"""Sampled continuous profiler for the slot servers — where do a step's
milliseconds go, and what compiled when?

TPU serving efficiency dies invisibly: a recompile storm (a gamma sweep,
an unwarmed bucket) stalls every stream for seconds with nothing in the
metrics to say why, and host/device overlap gaps leak milliseconds per
step that no per-request histogram can attribute. This module makes both
visible WITHOUT taxing the hot loop:

- **phase breakdown, sampled**: every Nth ``step()`` (``sample_every``)
  is broken into contiguous wall-time phases — ``schedule`` (admission +
  prefill chunk scheduling, host side), ``dispatch`` (handing the
  compiled leg to the device), ``device`` (a ``block_until_ready`` wait
  the SERVER issues only on sampled steps — the profiler itself never
  touches the device), ``materialize`` (token fetch + routing) — so an
  operator reads "step p50 is 9 ms: 1 host, 6 device, 2 fetch" instead
  of one opaque number. Un-sampled steps and the DISABLED profiler (the
  default) add zero syncs, zero uploads, and zero timing calls: the
  overlap double-buffer is never defeated by observability;
- **jit-compile tracking**: ``watch(leg, fn)`` wraps a compiled leg and
  attributes a call's wall time to compilation when it can tell a
  compile happened — via the jit function's own cache size where the
  JAX version exposes it (``_cache_size``), falling back to first-seen
  call-signature tracking (shape/dtype tuple) otherwise. Exposed as
  ``kubetpu_jit_recompiles_total{leg=...}`` and
  ``kubetpu_jit_compile_seconds_total{leg=...}`` counters, so a
  gamma-sweep or bucket-grid compile storm reads as a counter spike with
  seconds attached instead of a mystery stall.

Registry series (on the server's own registry):

    kubetpu_profile_sampled_steps_total
    kubetpu_profile_step_seconds_total          wall of sampled steps
    kubetpu_profile_phase_seconds_total{phase=...}
    kubetpu_jit_recompiles_total{leg=...}
    kubetpu_jit_compile_seconds_total{leg=...}

``summary()`` returns the same numbers structured for bench rows,
including ``coverage`` — the fraction of sampled-step wall time the
named phases account for (the acceptance bar is >= 0.9: a breakdown
that loses a tenth of the step is hiding the problem it exists to
find).

Stdlib only; imports nothing from kubetpu outside ``obs`` — the serving
layer owns every ``jax`` call (including the sampled-step sync).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from kubetpu.obs.registry import Registry


class StepRecord:
    """One sampled step: contiguous phase marks from ``begin_step``.
    ``mark(name)`` closes the current segment — phases tile the step, so
    their sum is the step wall minus only the inter-mark glue."""

    __slots__ = ("t0", "_last", "phases")

    def __init__(self) -> None:
        self.t0 = time.perf_counter()
        self._last = self.t0
        self.phases: Dict[str, float] = {}

    def mark(self, name: str) -> None:
        now = time.perf_counter()
        self.phases[name] = self.phases.get(name, 0.0) + (now - self._last)
        self._last = now


class ServingProfiler:
    """Sampled phase breakdown + compile tracking for one slot server."""

    def __init__(self, sample_every: int = 16,
                 registry: Optional[Registry] = None) -> None:
        if sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        self.sample_every = int(sample_every)
        self.registry = registry if registry is not None else Registry()
        self._lock = threading.Lock()
        self._step_i = 0
        self._sampled = 0
        self._wall = 0.0
        self._phases: Dict[str, float] = {}
        # compile watch state: leg -> {"sigs": set, "count": int, "s": float}
        self._legs: Dict[str, dict] = {}
        self._watched: Dict[str, Callable] = {}

    # -- sampling -------------------------------------------------------------

    def begin_step(self) -> Optional[StepRecord]:
        """Every ``sample_every``-th call returns a live ``StepRecord``
        (this step is SAMPLED — the server may afford one device sync);
        otherwise None, and the step must do no extra work at all."""
        with self._lock:
            i = self._step_i
            self._step_i += 1
        if i % self.sample_every:
            return None
        return StepRecord()

    def end_step(self, rec: StepRecord) -> None:
        wall = time.perf_counter() - rec.t0
        with self._lock:
            self._sampled += 1
            self._wall += wall
            for name, dt in rec.phases.items():
                self._phases[name] = self._phases.get(name, 0.0) + dt
        reg = self.registry
        reg.counter("kubetpu_profile_sampled_steps_total").inc()
        reg.counter("kubetpu_profile_step_seconds_total").inc(wall)
        for name, dt in rec.phases.items():
            reg.counter("kubetpu_profile_phase_seconds_total",
                        phase=name).inc(dt)

    # -- jit-compile tracking -------------------------------------------------

    @staticmethod
    def _cache_size(fn) -> Optional[int]:
        probe = getattr(fn, "_cache_size", None)
        if probe is None:
            return None
        try:
            return int(probe())
        except Exception:  # noqa: BLE001 — version drift must not crash serving
            return None

    @staticmethod
    def _signature(args, kwargs) -> tuple:
        def one(a):
            shape = getattr(a, "shape", None)
            if shape is not None:
                return ("arr", tuple(shape), str(getattr(a, "dtype", "")))
            if a is None or isinstance(a, (bool, int, float, str)):
                return ("lit", type(a).__name__)
            return ("obj", type(a).__name__)

        return (tuple(one(a) for a in args),
                tuple(sorted((k, one(v)) for k, v in kwargs.items())))

    def _note_compile(self, leg: str, seconds: float) -> None:
        with self._lock:
            st = self._legs.setdefault(leg, {"sigs": set(), "count": 0,
                                             "s": 0.0})
            st["count"] += 1
            st["s"] += seconds
        self.registry.counter("kubetpu_jit_recompiles_total", leg=leg).inc()
        self.registry.counter("kubetpu_jit_compile_seconds_total",
                              leg=leg).inc(seconds)

    def watch(self, leg: str, fn: Callable) -> Callable:
        """Wrap a compiled leg: a call that triggers a compile (cache
        growth, or an unseen call signature on JAX versions without a
        cache probe) increments the leg's recompile counter and adds the
        call's wall time to its compile seconds. Idempotent per *leg* —
        re-watching returns the SAME wrapper so call sites may wrap
        unconditionally (the paged speculative round leg is re-fetched
        every step). Re-watching the same leg name with a DIFFERENT
        function builds a fresh wrapper over the new function (sharing
        the leg's counters) — returning the cached one would silently
        substitute the old callable at the new call site."""
        cached = self._watched.get(leg)
        if cached is not None and cached.__wrapped__ is fn:
            return cached
        profiler = self
        state = self._legs.setdefault(leg, {"sigs": set(), "count": 0,
                                            "s": 0.0})

        def wrapped(*args, **kwargs):
            before = profiler._cache_size(fn)
            if before is None:
                sig = profiler._signature(args, kwargs)
                fresh = sig not in state["sigs"]
                if fresh:
                    state["sigs"].add(sig)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            if before is not None:
                after = profiler._cache_size(fn)
                fresh = after is not None and after > before
            if fresh:
                profiler._note_compile(leg, time.perf_counter() - t0)
            return out

        wrapped.__wrapped__ = fn  # type: ignore[attr-defined]
        self._watched[leg] = wrapped
        return wrapped

    # -- reporting ------------------------------------------------------------

    def summary(self) -> dict:
        """Structured snapshot for bench rows / operators: sampled-step
        count, total wall, per-phase seconds + fraction-of-wall,
        ``coverage`` (sum of phase fractions), and per-leg recompile
        count + compile seconds."""
        with self._lock:
            phases = dict(self._phases)
            wall = self._wall
            sampled = self._sampled
            steps = self._step_i
            legs = {leg: {"recompiles": st["count"],
                          "compile_seconds": round(st["s"], 4)}
                    for leg, st in self._legs.items() if st["count"]}
        phase_out = {
            name: {"seconds": round(dt, 4),
                   "frac": round(dt / wall, 4) if wall else 0.0}
            for name, dt in sorted(phases.items())
        }
        covered = sum(phases.values())
        return {
            "sample_every": self.sample_every,
            "steps": steps,
            "sampled_steps": sampled,
            "sampled_wall_s": round(wall, 4),
            "phases": phase_out,
            "coverage": round(covered / wall, 4) if wall else 0.0,
            "recompiles": legs,
        }
