"""Resource-key translation: wrap flat per-device request keys into the
hierarchical grouping a node advertises.

Re-creation of ``resource.TranslateResource(nodeRes, contReq, group, base)``
from the (non-vendored) KubeDevice-API, whose semantics are pinned by its two
call sites in the reference: stage-2 ``TranslateResource(node, req, "gpugrp0",
"gpu")`` and stage-3 ``TranslateResource(node, req, "gpugrp1", "gpugrp0")``
(``gpuschedulerplugin/gpu.go:55-58``) — "rewrites request keys one hierarchy
level up to match the node's advertised grouping" (SURVEY.md §1).

Grammar: a grouped key looks like

    resource/group/[<grp1>/<j>/][<grp0>/<i>/]<base>/<id>/<suffix...>

Wrapping inserts ``<group>/<idx>/`` immediately before the ``<base>/``
segment. Synthetic group indices pack the requested base ids (in sorted
order) into groups shaped like the node's advertised grouping (groups taken
largest-first), so the rewritten request can bin-pack onto the node.
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

from kubetpu.api.types import ResourceList


# Compiled-regex cache: the scheduler hot path (SURVEY.md §3.3) calls this
# per pod x node; per-call re.compile would dominate (SURVEY.md §7 "hard
# parts", reference compiles regexes inside the call at gpu.go:18,131,275).
_RE_CACHE: Dict[Tuple[str, str], "re.Pattern[str]"] = {}


def _seg_re(group_name: str, base_name: str) -> "re.Pattern[str]":
    key = (group_name, base_name)
    pat = _RE_CACHE.get(key)
    if pat is None:
        # captures: 1 = everything before <base>/<id>, 2 = base id, 3 = rest
        pat = re.compile(r"^(.*?)" + re.escape(base_name) + r"/([^/]+)/(.*)$")
        _RE_CACHE[key] = pat
    return pat


def _group_sizes(node_resources: ResourceList, group_name: str, base_name: str) -> List[int]:
    """Sizes (in distinct base ids) of each ``<group_name>`` group the node
    advertises, sorted descending — the packing template."""
    pat = _RE_CACHE.get(("grpsz", group_name, base_name))  # type: ignore[call-overload]
    if pat is None:
        pat = re.compile(
            r"/" + re.escape(group_name) + r"/([^/]+)/.*" + re.escape(base_name) + r"/([^/]+)/"
        )
        _RE_CACHE[("grpsz", group_name, base_name)] = pat  # type: ignore[index]
    groups: Dict[str, set] = {}
    for res in node_resources:
        m = pat.search(res)
        if m:
            groups.setdefault(m.group(1), set()).add(m.group(2))
    return sorted((len(v) for v in groups.values()), reverse=True)


def translate_resource(
    node_resources: ResourceList,
    container_requests: ResourceList,
    group_name: str,
    base_name: str,
) -> Tuple[bool, ResourceList]:
    """Wrap request keys containing ``<base_name>/`` but not ``<group_name>/``
    into synthetic ``<group_name>/<idx>/`` groups matching the node's shape.

    Returns ``(modified, new_requests)`` mirroring the reference call sites
    (``gpu.go:55-58``). No-op when the node does not advertise the grouping
    or every request key is already grouped.
    """
    sizes = _group_sizes(node_resources, group_name, base_name)
    if not sizes:
        return False, container_requests

    base_pat = _seg_re(group_name, base_name)
    group_seg = group_name + "/"

    # Collect base ids needing a wrap; keys already grouped pass through.
    to_wrap: Dict[str, List[str]] = {}  # base id -> request keys
    passthrough: ResourceList = {}
    for key, val in container_requests.items():
        m = base_pat.match(key)
        if m and group_seg not in m.group(1):
            to_wrap.setdefault(m.group(2), []).append(key)
        else:
            passthrough[key] = val

    if not to_wrap:
        return False, container_requests

    # Pack sorted base ids into synthetic groups, largest node group first.
    assignment: Dict[str, int] = {}
    gi, filled = 0, 0
    for base_id in sorted(to_wrap):
        cap = sizes[gi % len(sizes)]
        if filled >= cap:
            gi, filled = gi + 1, 0
            cap = sizes[gi % len(sizes)]
        assignment[base_id] = gi
        filled += 1

    new_requests: ResourceList = dict(passthrough)
    for base_id, keys in to_wrap.items():
        idx = assignment[base_id]
        for key in keys:
            m = base_pat.match(key)
            assert m is not None
            wrapped = (
                m.group(1)
                + group_name
                + "/"
                + str(idx)
                + "/"
                + base_name
                + "/"
                + m.group(2)
                + "/"
                + m.group(3)
            )
            new_requests[wrapped] = container_requests[key]
    return True, new_requests
