"""Device-plugin interface of the KubeDevice-API contract.

Reference: ``device.Device`` implemented by the NVIDIA manager
(``nvidiagpuplugin/gpu/nvidia/nvidia_gpu_manager.go:35-47,185-241``), loaded
by the CRI shim via ``plugin.Open`` + ``CreateDevicePlugin`` symbol lookup
(``nvidiagpuplugin/plugin/nvidiagpu.go:8-10``, ``cmd/main.go:23``).

The Go ``--buildmode=plugin`` shared-object mechanism becomes a Python
module-factory contract here (SURVEY.md §7): a plugin module exports
``create_device_plugin() -> Device``; ``create_device_from_plugin`` loads it
by import path or file path.
"""

from __future__ import annotations

import importlib
import importlib.util
from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from kubetpu.api.types import ContainerInfo, NodeInfo, PodInfo


@dataclass
class Mount:
    """A volume mount handed to the container runtime (reference:
    ``device.Mount``, used in the Allocate return tuple)."""

    name: str
    host_path: str
    container_path: str
    read_only: bool = True


# Allocate's return tuple: (mounts, device nodes, env vars)
# Reference returns ([]devtypes.Mount, []string, map[string]string, error)
# (nvidia_gpu_manager.go:216-241).
AllocateResult = Tuple[List[Mount], List[str], Dict[str, str]]


class Device(ABC):
    """A node-agent device manager (reference: KubeDevice-API ``device.Device``,
    surface inferred at SURVEY.md §1: New/Start/UpdateNodeInfo/Allocate/GetName)."""

    @abstractmethod
    def new(self) -> None:
        """Initialize internal state (reference New, nvidia_gpu_manager.go:40-47)."""

    @abstractmethod
    def start(self) -> None:
        """Probe hardware; must not raise on probe failure — the node degrades
        to zero devices instead (reference Start, nvidia_gpu_manager.go:185-188)."""

    @abstractmethod
    def update_node_info(self, node_info: NodeInfo) -> None:
        """Advertise capacity/allocatable, scalar + grouped topology keys
        (reference UpdateNodeInfo, nvidia_gpu_manager.go:191-213)."""

    @abstractmethod
    def allocate(self, pod: PodInfo, container: ContainerInfo) -> AllocateResult:
        """Turn ``container.allocate_from`` into device nodes + env for the
        container runtime (reference Allocate, nvidia_gpu_manager.go:216-241)."""

    @abstractmethod
    def get_name(self) -> str:
        """Plugin name, e.g. "tpu" (reference GetName)."""


def create_device_from_plugin(path: str) -> Device:
    """Load a device plugin and call its ``create_device_plugin`` factory.

    *path* is either a dotted module path (``kubetpu.device.plugin``) or a
    filesystem path to a ``.py`` file — the analog of
    ``device.CreateDeviceFromPlugin("/usr/local/KubeExt/devices/...so")``
    (reference ``cmd/main.go:23``).
    """
    mod = _load_module(path)
    factory = getattr(mod, "create_device_plugin", None)
    if factory is None:
        raise AttributeError(f"plugin {path!r} exports no create_device_plugin")
    return factory()


def _load_module(path: str):
    if path.endswith(".py"):
        spec = importlib.util.spec_from_file_location("kubetpu_plugin_" + str(abs(hash(path))), path)
        if spec is None or spec.loader is None:
            raise ImportError(f"cannot load plugin from {path!r}")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    return importlib.import_module(path)
