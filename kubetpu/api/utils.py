"""Leveled logging + small map utilities of the KubeDevice-API contract.

Reference usage: ``utils.Logf(level, fmt, ...)``, ``utils.Errorf``,
``utils.Logb(level) bool``, ``utils.SortedStringKeys(map) []string``
(``gpuschedulerplugin/gpu.go:62,125,133``, ``gpuplugintypes/typeutils.go:66-72``).
Observed levels 0-5: errors at 0, flow at 3-4, dumps at 5 (SURVEY.md §5.5).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Iterable, List, Mapping

_LOCK = threading.Lock()
_LEVEL = int(os.environ.get("KUBETPU_LOG_LEVEL", "1"))
_STREAM = sys.stderr


def set_log_level(level: int) -> None:
    global _LEVEL
    _LEVEL = level


def get_log_level() -> int:
    return _LEVEL


def logb(level: int) -> bool:
    """True if messages at *level* would be emitted (reference: utils.Logb)."""
    return level <= _LEVEL


def logf(level: int, fmt: str, *args: object) -> None:
    """Leveled printf-style log (reference: utils.Logf)."""
    if not logb(level):
        return
    msg = (fmt % args) if args else fmt
    with _LOCK:
        _STREAM.write("kubetpu[%d] %.3f %s\n" % (level, time.time(), msg))


def errorf(fmt: str, *args: object) -> None:
    """Error log, always emitted (reference: utils.Errorf; errors at level 0)."""
    msg = (fmt % args) if args else fmt
    with _LOCK:
        _STREAM.write("kubetpu[E] %.3f %s\n" % (time.time(), msg))


def sorted_string_keys(m: Mapping[str, object] | Iterable[str]) -> List[str]:
    """Sorted list of string keys (reference: utils.SortedStringKeys).

    Deterministic iteration order over resource maps is load-bearing: the
    auto-topology index synthesis and tree construction depend on it
    (reference ``gpu.go:133,149``).
    """
    if isinstance(m, Mapping):
        return sorted(str(k) for k in m.keys())
    return sorted(str(k) for k in m)
