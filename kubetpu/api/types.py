"""Core shared types of the KubeDevice-API contract.

Semantics inferred from the reference's usage sites (SURVEY.md §1):
``types.ResourceList`` iteration/assignment (reference
``gpuschedulerplugin/gpu.go:16-34``), ``types.NodeInfo`` construction
(``nvidiagpuplugin/gpu/nvidia/nvidia_gpu_manager.go:200-203``),
``types.PodInfo``/``ContainerInfo`` shapes (``gpuschedulerplugin/gpu.go:75-123``),
``DeviceGroupPrefix == "resource/group"`` (cross-check of
``gpuschedulerplugin/gpu.go:286`` against literal expected keys in
``gpuschedulerplugin/gpu_test.go:79-81``), and ``AddGroupResource``
(``nvidia_gpu_manager.go:206-209``).

Resource names form the system's wire format. The grouped-resource grammar is

    resource/group/<grp1name>/<j>/<grp0name>/<i>/<res>/<id>/<suffix>

e.g. ``resource/group/tpugrp1/0/tpugrp0/0/tpu/0/cards``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

# A resource name, e.g. "kubedevice/tpu" or
# "resource/group/tpugrp1/0/tpugrp0/0/tpu/4/cards".
ResourceName = str

# Map resource name -> integer quantity (reference: types.ResourceList,
# map[ResourceName]int64).
ResourceList = Dict[ResourceName, int]

# Map "from" request key -> "to" node-resource key, filled by the group
# scheduler at allocation time (reference: types.ResourceLocation, usage at
# nvidia_gpu_manager_test.go:38-47).
ResourceLocation = Dict[ResourceName, ResourceName]

# Namespace prefix for grouped/topology-shaped resources (reference:
# types.DeviceGroupPrefix, value proven by gpu_test.go:79-81).
DeviceGroupPrefix: ResourceName = "resource/group"


def add_group_resource(reslist: ResourceList, suffix: str, val: int) -> None:
    """Insert ``DeviceGroupPrefix + "/" + suffix -> val`` into *reslist*.

    Reference: ``types.AddGroupResource`` call sites
    ``nvidia_gpu_manager.go:206-209`` vs. expected keys
    ``nvidia_gpu_manager_test.go:125-126``.
    """
    reslist[DeviceGroupPrefix + "/" + suffix] = val


@dataclass
class ContainerInfo:
    """Per-container resource requests and allocation results.

    Reference: ``types.ContainerInfo{Requests, KubeRequests, DevRequests,
    AllocateFrom}`` (usage ``gpuschedulerplugin/gpu.go:75-92``,
    ``nvidia_gpu_manager.go:221-227``).

    - ``requests``:      device-native requests (e.g. ``kubedevice/tpu: 4``).
    - ``kube_requests``: requests as seen by vanilla Kubernetes.
    - ``dev_requests``:  topology-shaped requests produced by the scheduler
                         plugin's translation.
    - ``allocate_from``: request-key -> node-resource-key mapping filled by
                         the group scheduler; consumed by ``Device.allocate``.
    """

    requests: ResourceList = field(default_factory=dict)
    kube_requests: ResourceList = field(default_factory=dict)
    dev_requests: ResourceList = field(default_factory=dict)
    allocate_from: ResourceLocation = field(default_factory=dict)

    def copy(self) -> "ContainerInfo":
        return ContainerInfo(
            requests=dict(self.requests),
            kube_requests=dict(self.kube_requests),
            dev_requests=dict(self.dev_requests),
            allocate_from=dict(self.allocate_from),
        )


@dataclass
class PodInfo:
    """Pod-level requests plus its containers.

    Reference: ``types.PodInfo{Name, Requests, InitContainers,
    RunningContainers}`` (usage ``gpuschedulerplugin/gpu.go:94-123``,
    ``gpu_test.go:61-71``, ``nvidia_gpu_manager.go:228``).
    """

    name: str = ""
    node_name: str = ""
    requests: ResourceList = field(default_factory=dict)
    init_containers: Dict[str, ContainerInfo] = field(default_factory=dict)
    running_containers: Dict[str, ContainerInfo] = field(default_factory=dict)

    def copy(self) -> "PodInfo":
        return PodInfo(
            name=self.name,
            node_name=self.node_name,
            requests=dict(self.requests),
            init_containers={k: v.copy() for k, v in self.init_containers.items()},
            running_containers={k: v.copy() for k, v in self.running_containers.items()},
        )


@dataclass
class NodeInfo:
    """A node's advertised resources, device-native and kube-native.

    Reference: ``types.NodeInfo{Capacity, Allocatable, KubeCap, KubeAlloc}``
    + ``types.NewNodeInfo()`` (usage ``nvidia_gpu_manager.go:200-203``,
    ``cmd/main.go:37``).
    """

    name: str = ""
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    kube_cap: ResourceList = field(default_factory=dict)
    kube_alloc: ResourceList = field(default_factory=dict)

    def copy(self) -> "NodeInfo":
        return NodeInfo(
            name=self.name,
            capacity=dict(self.capacity),
            allocatable=dict(self.allocatable),
            kube_cap=dict(self.kube_cap),
            kube_alloc=dict(self.kube_alloc),
        )


def new_node_info(name: str = "") -> NodeInfo:
    """Reference: ``types.NewNodeInfo()`` (``cmd/main.go:37``)."""
    return NodeInfo(name=name)
