"""Re-creation of the KubeDevice-API surface the reference compiles against.

The reference repo (microsoft/KubeGPU) imports
``github.com/Microsoft/KubeDevice-API/pkg/{types,utils,resource,device,
devicescheduler}`` which is *not* vendored there (SURVEY.md §1, "the missing
layer"). This package re-creates that contract from its usage sites, cited
per symbol in the submodules.
"""

from kubetpu.api import types, utils, resource, device, devicescheduler  # noqa: F401
