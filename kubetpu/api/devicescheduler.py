"""Scheduler-plugin interface of the KubeDevice-API contract.

Reference: ``devicescheduler.DeviceScheduler`` implemented by
``gpuschedulerplugin/gpu_scheduler.go:21-71`` and loaded via
``CreateDeviceSchedulerPlugin`` (``gpuschedulerplugin/plugin/gpuscheduler.go``).
"""

from __future__ import annotations

import importlib
import importlib.util
from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import List, Tuple

from kubetpu.api.types import NodeInfo, PodInfo


@dataclass
class PredicateFailureReason:
    """Why a pod does not fit a node (reference:
    ``devicescheduler.PredicateFailureReason``, gpu_scheduler.go:34)."""

    resource_name: str = ""
    requested: int = 0
    used: int = 0
    capacity: int = 0
    message: str = ""


# (fits, failure reasons, score) — reference PodFitsDevice return triple.
FitResult = Tuple[bool, List[PredicateFailureReason], float]


class DeviceScheduler(ABC):
    """A device-specific scheduler plugin (reference surface:
    AddNode/RemoveNode/PodFitsDevice/PodAllocate/TakePodResources/
    ReturnPodResources/GetName/UsingGroupScheduler, gpu_scheduler.go)."""

    @abstractmethod
    def add_node(self, node_name: str, node_info: NodeInfo) -> None: ...

    @abstractmethod
    def remove_node(self, node_name: str) -> None: ...

    @abstractmethod
    def pod_fits_device(
        self, node_info: NodeInfo, pod_info: PodInfo, fill_allocate_from: bool
    ) -> FitResult: ...

    @abstractmethod
    def pod_allocate(self, node_info: NodeInfo, pod_info: PodInfo) -> None:
        """Raise on failure (reference returns error, gpu_scheduler.go:46-55)."""

    @abstractmethod
    def take_pod_resources(self, node_info: NodeInfo, pod_info: PodInfo) -> None: ...

    @abstractmethod
    def return_pod_resources(self, node_info: NodeInfo, pod_info: PodInfo) -> None: ...

    @abstractmethod
    def get_name(self) -> str: ...

    @abstractmethod
    def using_group_scheduler(self) -> bool:
        """True to delegate bin-packing/AllocateFrom fill to the core group
        scheduler (reference gpu_scheduler.go:69-71; kubetpu implements that
        group scheduler in ``kubetpu.core``)."""

    def perfect_score(self, pod_info: PodInfo) -> "float | None":
        """The provably-best fit score this scheduler can award *pod_info*
        on ANY node, or None when no tight bound exists. The core's
        predicate sweep stops early once a node reaches the sum of all
        schedulers' bounds — at cluster scale (hundreds of nodes) that
        turns the common 'a perfectly-contiguous node exists' case from
        O(nodes) into O(nodes scanned until the first perfect one).
        Default None: never stop early (kubetpu extension; the reference's
        external core has no ranking at all, gpu_scheduler.go:34-44)."""
        return None


def create_device_scheduler_from_plugin(path: str) -> DeviceScheduler:
    """Load a scheduler plugin module and call its
    ``create_device_scheduler_plugin`` factory (analog of the Go
    ``plugin.Open`` + symbol lookup, ``Makefile:12``)."""
    if path.endswith(".py"):
        spec = importlib.util.spec_from_file_location(
            "kubetpu_sched_plugin_" + str(abs(hash(path))), path
        )
        if spec is None or spec.loader is None:
            raise ImportError(f"cannot load plugin from {path!r}")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    else:
        mod = importlib.import_module(path)
    factory = getattr(mod, "create_device_scheduler_plugin", None)
    if factory is None:
        raise AttributeError(f"plugin {path!r} exports no create_device_scheduler_plugin")
    return factory()
