"""TPU kernels (Pallas) for the hot ops."""

from kubetpu.ops.flash_attention import flash_attention
from kubetpu.ops.paged_attention import paged_attention, paged_attention_chunk

__all__ = ["flash_attention", "paged_attention", "paged_attention_chunk"]
