"""TPU kernels (Pallas) for the hot ops."""

from kubetpu.ops.flash_attention import flash_attention

__all__ = ["flash_attention"]
