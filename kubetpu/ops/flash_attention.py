"""Blocked causal flash attention as a Pallas TPU kernel.

The hot op of the flagship model, written for the hardware: the score
matrix never materializes in HBM — each grid step streams one query block
through all its (causal) key/value blocks in VMEM, accumulating the
numerically-stable running softmax (max + normalizer) in registers, with
both matmuls on the MXU in float32 accumulation. Memory traffic per head
drops from O(S^2) to O(S * D).

Causality is exploited at *block* granularity: the k-block loop runs only to
the diagonal (``qi // kq_ratio + 1`` iterations), masking inside the
diagonal block only — upper-triangle blocks are never read, which halves
the FLOPs and bandwidth vs. masked dense attention.

Interface matches the model's attention core: (B, S, H, D) -> (B, S, H, D).
Training works through a ``jax.custom_vjp`` whose backward recomputes via
the XLA dense reference (exact same math, so gradients are exact); a fused
backward kernel is the next optimization step.

Run with ``interpret=True`` for CPU tests (the Pallas interpreter), and
compiled on real TPU hardware otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int,
                  scale: float, seq_len: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # (block_q, D)
    d = q.shape[-1]

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(ki, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                              # (block_q, block_k)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = q_pos >= k_pos
        s = jnp.where(mask, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * correction + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    # causal: only blocks up to (and including) the diagonal
    num_k_blocks = (qi * block_q) // block_k + (block_q + block_k - 1) // block_k
    m, l, acc = jax.lax.fori_loop(0, num_k_blocks, body, (m0, l0, acc0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _flash_forward(q, k, v, block_q: int, block_k: int, interpret: bool):
    b, s, h, d = q.shape
    scale = d ** -0.5
    # (B, S, H, D) -> (B*H, S, D): one grid row per (batch, head)
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    bq = min(block_q, s)
    bk = min(block_k, s)
    assert s % bq == 0 and s % bk == 0, (s, bq, bk)

    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, block_q=bq, block_k=bk, scale=scale, seq_len=s
        ),
        grid=(b * h, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, s, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=interpret,
    )(qh, kh, vh)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, block_q: int = 128, block_k: int = 128,
                    interpret: bool = False):
    """Causal flash attention: (B, S, H, D) -> (B, S, H, D), drop-in for
    ``model.forward``'s ``attn_fn`` (wrap block sizes with functools.partial).
    """
    return _flash_forward(q, k, v, block_q, block_k, interpret)


def _fwd(q, k, v, block_q, block_k, interpret):
    return _flash_forward(q, k, v, block_q, block_k, interpret), (q, k, v)


def _bwd(block_q, block_k, interpret, res, g):
    # Exact gradients by recomputation through the XLA dense reference —
    # same math as the kernel, so d(out)/d(qkv) matches; a fused Pallas
    # backward is the next optimization.
    from kubetpu.jobs.model import dense_causal_attention

    q, k, v = res
    _, vjp = jax.vjp(dense_causal_attention, q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
