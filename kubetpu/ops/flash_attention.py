"""Blocked causal flash attention as a Pallas TPU kernel.

The hot op of the flagship model, written for the hardware: the score
matrix never materializes in HBM — each grid step streams one query block
through all its (causal) key/value blocks in VMEM, accumulating the
numerically-stable running softmax (max + normalizer) in registers, with
both matmuls on the MXU in float32 accumulation. Memory traffic per head
drops from O(S^2) to O(S * D).

Causality is exploited at *block* granularity: the k-block loop runs only to
the diagonal (``qi // kq_ratio + 1`` iterations), masking inside the
diagonal block only — upper-triangle blocks are never read, which halves
the FLOPs and bandwidth vs. masked dense attention.

Interface matches the model's attention core: (B, S, H, D) -> (B, S, H, D).
Training runs through fused FlashAttention-2-style backward kernels: the
forward additionally emits the per-row log-sum-exp; the dQ pass streams
causal k/v blocks per query block and the dK/dV pass streams query blocks
per key block, both recomputing P exactly from the lse — so neither
direction materializes the O(S^2) score matrix (fwd+bwd at seq 8192 runs
where the dense path OOMs).

Run with ``interpret=True`` for CPU tests (the Pallas interpreter), and
compiled on real TPU hardware otherwise. Interpret-mode gradients match the
dense reference to ~1e-3; compiled-on-TPU comparisons differ up to ~6e-3
relative because the XLA dense *reference* itself uses default-precision
(bf16 multipass) f32 matmuls.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q: int,
                  block_k: int, scale: float, seq_len: int, causal: bool,
                  window: int = 0):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # (block_q, D)
    d = q.shape[-1]

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(ki, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                              # (block_q, block_k)
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            mask = q_pos >= k_pos
            if window > 0:  # sliding window: see the last `window` positions
                mask &= q_pos - k_pos < window
            s = jnp.where(mask, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * correction + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    if causal:  # only blocks up to (and including) the diagonal
        num_k_blocks = (qi * block_q) // block_k + (block_q + block_k - 1) // block_k
    else:       # full visibility (ring attention's sub-diagonal blocks)
        num_k_blocks = seq_len // block_k
    # sliding window skips key blocks wholly LEFT of every row's window —
    # work per query block becomes O(window), not O(position)
    first_k = (
        jnp.maximum(0, qi * block_q - (window - 1)) // block_k
        if causal and window > 0 else 0
    )
    m, l, acc = jax.lax.fori_loop(first_k, num_k_blocks, body, (m0, l0, acc0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    # log-sum-exp per row (the softmax residual the backward kernels need);
    # stored (bq, 1) — TPU block tiling wants a trailing lane axis
    lse_ref[0] = m + jnp.log(l)


def _heads_layout(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _flash_forward(q, k, v, block_q: int, block_k: int, interpret: bool,
                   causal: bool = True, window: int = 0):
    """Returns (out (B,S,H,D), lse (B*H, S, 1)) — lse is the backward
    residual and the merge weight for ring-attention block combination."""
    if window > 0 and not causal:
        raise ValueError("window > 0 requires causal attention")
    b, s, h, d = q.shape
    scale = d ** -0.5
    # (B, S, H, D) -> (B*H, S, D): one grid row per (batch, head)
    qh, kh, vh = _heads_layout(q), _heads_layout(k), _heads_layout(v)

    bq = min(block_q, s)
    bk = min(block_k, s)
    assert s % bq == 0 and s % bk == 0, (s, bq, bk)

    out, lse = pl.pallas_call(
        functools.partial(
            _flash_kernel, block_q=bq, block_k=bk, scale=scale, seq_len=s,
            causal=causal, window=window,
        ),
        grid=(b * h, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, s, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, i: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qh, kh, vh)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3), lse


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_q: int, block_k: int, scale: float,
                         seq_len: int, causal: bool, window: int = 0):
    """dQ for one query block: stream the (causal or all) k/v blocks,
    recompute P from the saved log-sum-exp (FlashAttention-2 backward, dQ
    pass)."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                   # (bq, D)
    do = do_ref[0].astype(jnp.float32)                 # (bq, D)
    lse = lse_ref[0]                                   # (bq, 1)
    delta = delta_ref[0]                               # (bq, 1)
    d = q.shape[-1]
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(ki, dq):
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        # exact probs via lse. The clamp is a no-op for every score the lse
        # covers (s <= lse row-wise by construction) and bounds the ring's
        # INVISIBLE-step calls, whose scores the global lse does not cover —
        # without it exp() overflows to inf there and inf * 0-gate = NaN.
        p = jnp.exp(jnp.minimum(s - lse, 0.0))
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            mask = q_pos >= k_pos
            if window > 0:
                mask &= q_pos - k_pos < window
            p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale

    if causal:
        num_k_blocks = (qi * block_q) // block_k + (block_q + block_k - 1) // block_k
    else:
        num_k_blocks = seq_len // block_k
    first_k = (
        jnp.maximum(0, qi * block_q - (window - 1)) // block_k
        if causal and window > 0 else 0
    )
    dq = jax.lax.fori_loop(first_k, num_k_blocks, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, block_k: int,
                          scale: float, num_q_blocks: int, causal: bool,
                          window: int = 0):
    """dK/dV for one key block: stream the query blocks at or below the
    diagonal — or all of them when non-causal (FlashAttention-2 backward,
    dK/dV pass)."""
    kj = pl.program_id(1)
    k_blk = k_ref[0].astype(jnp.float32)               # (bk, D)
    v_blk = v_ref[0].astype(jnp.float32)               # (bk, D)
    d = k_blk.shape[-1]
    k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def body(qi, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q), :]    # (bq, 1)
        delta = delta_ref[0, pl.ds(qi * block_q, block_q), :]
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        # clamped for the same reason as the dQ kernel (ring invisible steps)
        p = jnp.exp(jnp.minimum(s - lse, 0.0))         # (bq, bk)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = q_pos >= k_pos
            if window > 0:
                mask &= q_pos - k_pos < window
            p = jnp.where(mask, p, 0.0)
        dv = dv + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        return dk, dv

    # first query block whose rows can see this key block; with a window,
    # also the LAST one (rows beyond k_pos + window - 1 see nothing here)
    first_qi = (kj * block_k) // block_q if causal else 0
    if causal and window > 0:
        last_qi = jnp.minimum(
            num_q_blocks,
            ((kj + 1) * block_k - 1 + (window - 1)) // block_q + 1,
        )
    else:
        last_qi = num_q_blocks
    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first_qi, last_qi, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, block_q, block_k, interpret,
                    causal: bool = True, window: int = 0):
    """Fused backward. With ``causal=False`` this also serves the ring
    attention's off-diagonal steps: *out*/*lse*/*g* are then the GLOBAL
    (merged) output, log-sum-exp and cotangent — the FlashAttention-2
    formulas are exact under a global lse, so the per-block contributions
    returned here sum to the full gradient across ring steps."""
    b, s, h, d = q.shape
    scale = d ** -0.5
    qh, kh, vh = _heads_layout(q), _heads_layout(k), _heads_layout(v)
    doh, oh = _heads_layout(g), _heads_layout(out)
    # per-row softmax correction term: D_i = sum_d dO_i * O_i, kept (BH,S,1)
    delta = jnp.sum(doh.astype(jnp.float32) * oh.astype(jnp.float32), axis=-1,
                    keepdims=True)

    bq = min(block_q, s)
    bk = min(block_k, s)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=bq, block_k=bk,
                          scale=scale, seq_len=s, causal=causal,
                          window=window),
        grid=(b * h, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),   # q
            pl.BlockSpec((1, s, d), lambda bh, i: (bh, 0, 0)),    # k
            pl.BlockSpec((1, s, d), lambda bh, i: (bh, 0, 0)),    # v
            pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),   # dO
            pl.BlockSpec((1, bq, 1), lambda bh, i: (bh, i, 0)),   # lse
            pl.BlockSpec((1, bq, 1), lambda bh, i: (bh, i, 0)),   # delta
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=interpret,
    )(qh, kh, vh, doh, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, block_q=bq, block_k=bk, scale=scale,
            num_q_blocks=s // bq, causal=causal, window=window,
        ),
        grid=(b * h, s // bk),
        in_specs=[
            pl.BlockSpec((1, s, d), lambda bh, j: (bh, 0, 0)),    # q
            pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),   # k
            pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),   # v
            pl.BlockSpec((1, s, d), lambda bh, j: (bh, 0, 0)),    # dO
            pl.BlockSpec((1, s, 1), lambda bh, j: (bh, 0, 0)),    # lse
            pl.BlockSpec((1, s, 1), lambda bh, j: (bh, 0, 0)),    # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        ],
        interpret=interpret,
    )(qh, kh, vh, doh, lse, delta)

    def back(x):
        return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)

    return back(dq), back(dk), back(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, block_q: int = 128, block_k: int = 128,
                    interpret: bool = False, causal: bool = True,
                    window: int = 0):
    """Flash attention: (B, S, H, D) -> (B, S, H, D), drop-in for
    ``model.forward``'s ``attn_fn`` (wrap block sizes with functools.partial).
    Causal by default; ``causal=False`` is full bidirectional visibility —
    the encoder/ViT-style core (and the ring's off-diagonal steps).
    ``window > 0`` (causal only) is sliding-window attention: each position
    sees the previous ``window`` positions including itself, and key blocks
    wholly outside every row's window are never read in EITHER direction —
    per-position work becomes O(window), the long-context local-attention
    trade. Training uses the fused FlashAttention-2-style backward kernels
    (dQ pass + dK/dV pass over the saved log-sum-exp) — no O(S^2)
    materialization in either direction.
    """
    out, _lse = _flash_forward(q, k, v, block_q, block_k, interpret, causal,
                               window)
    return out


def _fwd(q, k, v, block_q, block_k, interpret, causal, window):
    out, lse = _flash_forward(q, k, v, block_q, block_k, interpret, causal,
                              window)
    return out, (q, k, v, out, lse)


def _bwd(block_q, block_k, interpret, causal, window, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, block_q, block_k, interpret,
                           causal, window)


flash_attention.defvjp(_fwd, _bwd)
