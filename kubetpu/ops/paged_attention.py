"""Paged attention as a Pallas TPU kernel family (Round-15).

The decode-time hot ops of the paged KV cache (kubetpu.jobs.paged): one
(or T) query token(s) per slot attend the slot's sequence scattered
across pool pages. The XLA reference (`_attend_paged` /
`_attend_paged_chunk`) GATHERS the slot's pages into a contiguous
(B, max_pages*ps, H_kv, D) buffer every step — materialized HBM traffic
proportional to the cache size, and for kv_int8 pools an additional
materialized f32 dequant copy. This kernel family streams pages through
VMEM instead:

- grid (B, ceil(max_pages / pages_per_block)), sequential on TPU: for
  each slot, each block of ``pages_per_block`` logical pages is one grid
  step whose K/V blocks are selected by the PREFETCHED page table
  (``PrefetchScalarGridSpec`` — each page's index map reads
  ``table[b, blk*ppb + i]``, so the gather happens in the block loader,
  not in HBM). ``pages_per_block`` is the VMEM tile knob the
  ``pagedtune`` bench sweeps: a wider block gives the loader more DMA to
  overlap per step at the cost of VMEM residency. 1 is the shipped
  default;
- IN-KERNEL INT8 DEQUANT: an int8 pool hands the kernel (values int8,
  scales f32) page pairs; each tile dequantizes inside VMEM as
  ``values.astype(f32) * scales`` — elementwise-identical to the gather
  core's ``_gather_pages`` math, so dequantize-then-attend is preserved
  bit-for-bit at the point scores are formed and greedy decode through
  the kernel stays token-exact against the gather core. The materialized
  f32 copy of the gathered cache is gone entirely;
- MULTI-TOKEN CHUNK: ``paged_attention_chunk`` computes the causal
  T-query-per-slot attention of ``_attend_paged_chunk`` (query t at
  position pos+t sees keys <= pos+t) — the speculative (gamma+1)-token
  verify leg and chunked prefill's gathered-logical-pages attention run
  through the same page walk; the one-token decode kernel is its T == 1
  special case (one implementation, one soundness argument);
- BANDED MASK: ``window > 0`` adds the repo-wide band (key visible iff
  ``0 <= q_pos - k_pos < window``) and skips pages entirely below the
  band, which makes the RING page table sound through the kernel for
  plain paged decode: aliased stale copies sit outside every band and
  are masked exactly as in ``_attend_paged``;
- flash-style online softmax across page blocks: running (max,
  normalizer) and the output accumulator live in VMEM scratch, carried
  across the grid; pages past the visible range (or unmapped) are
  skipped via ``pl.when`` — their block load is clamped to page 0 and
  ignored.

Interpret mode (CPU tests + `make spec-check`/`prefix-check` kernel
arms) pins exact agreement with the gather core; compiled validation
runs in scripts/tpu_smoke.py on real hardware.

Reference: none in /root/reference (no inference stack, SURVEY.md §2);
the paged layout follows the public vLLM pattern, re-shaped for TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _paged_attn_kernel(
    table_ref, pos_ref,            # scalar-prefetch operands (SMEM)
    q_ref, *refs,                  # kv blocks (VMEM), o_ref, scratch
    ps: int, max_pages: int, scale: float, t: int, window: int,
    int8: bool, ppb: int,
):
    per = 4 if int8 else 2
    kv_refs = refs[: per * ppb]
    o_ref = refs[per * ppb]
    stats_ref = refs[per * ppb + 1]     # (2, T, H) running max / norm
    acc_ref = refs[per * ppb + 2]       # (T, H, D)

    b = pl.program_id(0)
    blk = pl.program_id(1)

    @pl.when(blk == 0)
    def _init():
        stats_ref[0, :, :] = jnp.full_like(stats_ref[0, :, :], NEG_INF)
        stats_ref[1, :, :] = jnp.zeros_like(stats_ref[1, :, :])
        acc_ref[...] = jnp.zeros_like(acc_ref)

    pos = pos_ref[b]
    q = q_ref[0].astype(jnp.float32) * scale              # (T, H, D)
    h, d = q.shape[1], q.shape[2]

    for i in range(ppb):
        lp = blk * ppb + i
        page_lo = lp * ps
        valid = jnp.logical_and(
            page_lo <= pos + (t - 1),
            table_ref[b, jnp.minimum(lp, max_pages - 1)] >= 0,
        )
        valid = jnp.logical_and(valid, lp < max_pages)
        if window > 0:
            # the page's last key must reach the lowest band's floor
            # (smallest q_pos = pos): pages entirely below every band
            # are skipped, the kernel-side twin of the ring soundness
            valid = jnp.logical_and(valid, page_lo + ps - 1 > pos - window)

        @pl.when(valid)
        def _page(i=i, lp=lp):
            if int8:
                k8, ksc, v8, vsc = kv_refs[4 * i: 4 * i + 4]
                # bit-matches _gather_pages: convert THEN scale, f32 —
                # the dequantize-then-attend order the parity pins rely on
                k = k8[0].astype(jnp.float32) * ksc[0]
                v = v8[0].astype(jnp.float32) * vsc[0]
            else:
                k_r, v_r = kv_refs[2 * i: 2 * i + 2]
                k = k_r[0].astype(jnp.float32)            # (ps, Hkv, D)
                v = v_r[0].astype(jnp.float32)
            h_kv = k.shape[1]
            g = h // h_kv

            # grouped-query: H = (Hkv, g) major order, the gather core's
            # reshape convention
            qg = q.reshape(t, h_kv, g, d).transpose(1, 0, 2, 3)
            qg = qg.reshape(h_kv, t * g, d)
            kt = k.transpose(1, 0, 2)                     # (Hkv, ps, D)
            s = jax.lax.dot_general(
                qg, kt, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )                                             # (Hkv, T*g, ps)
            s = s.reshape(h_kv, t, g, ps).transpose(1, 0, 2, 3)
            s = s.reshape(t, h, ps)
            k_pos = page_lo + jax.lax.broadcasted_iota(
                jnp.int32, (t, h, ps), 2)
            q_pos = pos + jax.lax.broadcasted_iota(
                jnp.int32, (t, h, ps), 0)
            mask = k_pos <= q_pos
            if window > 0:
                mask = jnp.logical_and(mask, q_pos - k_pos < window)
            s = jnp.where(mask, s, NEG_INF)

            m_prev = stats_ref[0, :, :]                   # (T, H)
            l_prev = stats_ref[1, :, :]
            m_new = jnp.maximum(m_prev, s.max(axis=2))
            alpha = jnp.exp(m_prev - m_new)
            # exp(min(s - m, 0)): s <= m by construction, the guard keeps
            # a +inf out of the accumulator if a NaN/overflow sneaks in
            pexp = jnp.exp(jnp.minimum(s - m_new[:, :, None], 0.0))
            l_new = l_prev * alpha + pexp.sum(axis=2)
            vt = v.transpose(1, 0, 2)                     # (Hkv, ps, D)
            pg = pexp.reshape(t, h_kv, g, ps).transpose(1, 0, 2, 3)
            pg = pg.reshape(h_kv, t * g, ps)
            o = jax.lax.dot_general(
                pg, vt, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )                                             # (Hkv, T*g, D)
            o = o.reshape(h_kv, t, g, d).transpose(1, 0, 2, 3)
            o = o.reshape(t, h, d)
            acc_ref[...] = acc_ref[...] * alpha[:, :, None] + o
            stats_ref[0, :, :] = m_new
            stats_ref[1, :, :] = l_new

    @pl.when(blk == pl.num_programs(1) - 1)
    def _finalize():
        l = jnp.maximum(stats_ref[1, :, :], 1e-30)
        o_ref[0] = (acc_ref[...] / l[:, :, None]).astype(o_ref.dtype)


def _paged_attention_call(q, k_pages_l, v_pages_l, table, pos, *,
                          window: int, pages_per_block: int,
                          interpret: bool):
    """Shared pallas_call builder: q (B, T, H, D); pools dense arrays or
    int8 (values, scales) pairs; table (B, max_pages) int32 (-1 =
    unmapped); pos (B,) position of q[:, 0]. Returns (B, T, H, D)."""
    b, t, h, d = q.shape
    int8 = isinstance(k_pages_l, tuple)
    if int8:
        k8, ksc = k_pages_l
        v8, vsc = v_pages_l
        ps, h_kv = k8.shape[1], k8.shape[2]
    else:
        ps, h_kv = k_pages_l.shape[1], k_pages_l.shape[2]
    max_pages = table.shape[1]
    ppb = max(1, min(int(pages_per_block), max_pages))
    n_blocks = (max_pages + ppb - 1) // ppb
    scale = d ** -0.5

    def page_index(i):
        def idx(b_i, blk, table_ref, pos_ref):
            # past-the-end pages of a ragged final block clamp to the
            # last table column; the kernel's `lp < max_pages` guard
            # ignores whatever loads
            lp = jnp.minimum(blk * ppb + i, max_pages - 1)
            return (jnp.maximum(table_ref[b_i, lp], 0), 0, 0, 0)
        return idx

    def fixed(b_i, blk, table_ref, pos_ref):
        return (b_i, 0, 0, 0)

    kv_specs, kv_ops = [], []
    for i in range(ppb):
        if int8:
            kv_specs += [
                pl.BlockSpec((1, ps, h_kv, d), page_index(i)),
                pl.BlockSpec((1, ps, h_kv, 1), page_index(i)),
                pl.BlockSpec((1, ps, h_kv, d), page_index(i)),
                pl.BlockSpec((1, ps, h_kv, 1), page_index(i)),
            ]
            kv_ops += [k8, ksc, v8, vsc]
        else:
            kv_specs += [
                pl.BlockSpec((1, ps, h_kv, d), page_index(i)),
                pl.BlockSpec((1, ps, h_kv, d), page_index(i)),
            ]
            kv_ops += [k_pages_l, v_pages_l]

    kernel = functools.partial(
        _paged_attn_kernel, ps=ps, max_pages=max_pages, scale=scale,
        t=t, window=int(window), int8=int8, ppb=ppb,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n_blocks),
        in_specs=[pl.BlockSpec((1, t, h, d), fixed)] + kv_specs,
        out_specs=pl.BlockSpec((1, t, h, d), fixed),
        scratch_shapes=[
            pltpu.VMEM((2, t, h), jnp.float32),
            pltpu.VMEM((t, h, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, t, h, d), q.dtype),
        interpret=interpret,
    )(table, pos, q, *kv_ops)


def paged_attention(q, k_pages_l, v_pages_l, table, pos, window: int = 0,
                    pages_per_block: int = 1, interpret: bool = False):
    """Drop-in for ``kubetpu.jobs.paged._attend_paged`` (its ``attend=``
    plug point): q (B, H, D); pages (P, ps, H_kv, D) dense or int8
    (values, scales (..., H_kv, 1)) pairs; table (B, max_pages) int32
    with -1 for unmapped; pos (B,) query positions; ``window > 0`` = the
    banded mask. Returns (B, H, D) — the T == 1 case of the chunk
    kernel."""
    out = _paged_attention_call(
        q[:, None], k_pages_l, v_pages_l, table, pos,
        window=window, pages_per_block=pages_per_block, interpret=interpret,
    )
    return out[:, 0]


def paged_attention_chunk(q, k_pages_l, v_pages_l, table, pos,
                          pages_per_block: int = 1,
                          interpret: bool = False):
    """Drop-in for ``kubetpu.jobs.paged._attend_paged_chunk``: causal
    T-query-per-slot attention through the page table — q (B, T, H, D)
    at per-slot positions ``pos..pos+T-1``; same pool layouts as
    ``paged_attention``. No ``window``: the speculative server refuses
    windowed configs (ring aliasing vs overshoot writes) and windowed
    chunked prefill needs the gather core's gather-before-write order."""
    return _paged_attention_call(
        q, k_pages_l, v_pages_l, table, pos,
        window=0, pages_per_block=pages_per_block, interpret=interpret,
    )
