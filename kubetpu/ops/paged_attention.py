"""Paged attention as a Pallas TPU kernel.

The decode-time hot op of the paged KV cache (kubetpu.jobs.paged): one
query token per slot attends its sequence scattered across pool pages.
The XLA reference (`_attend_paged`) GATHERS the slot's pages into a
contiguous (B, max_pages*ps, H_kv, D) buffer every step — materialized
HBM traffic proportional to the cache size. This kernel streams pages
through VMEM instead:

- grid (B, max_pages), sequential on TPU: for each slot, each logical
  page is one grid step whose K/V block is selected by the PREFETCHED
  page table (``PrefetchScalarGridSpec`` — the index map reads
  ``table[b, p]``, so the gather happens in the block loader, not in HBM);
- flash-style online softmax across pages: running (max, normalizer) and
  the output accumulator live in VMEM scratch, carried across the page
  grid steps; pages past the slot's position (or unmapped) are skipped
  via ``pl.when`` — their block load is clamped to page 0 and ignored;
- grouped-query aware: H query heads attend H_kv cached heads in groups
  without expanding the cache (same layout contract as the XLA path).

Interpret mode (CPU tests) pins exact agreement with `_attend_paged`;
compiled validation runs in scripts/tpu_smoke.py on real hardware.

Reference: none in /root/reference (no inference stack, SURVEY.md §2);
the paged layout follows the public vLLM pattern, re-shaped for TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _paged_attn_kernel(
    table_ref, pos_ref,            # scalar-prefetch operands (SMEM)
    q_ref, k_ref, v_ref,           # blocks (VMEM)
    o_ref,                         # output block (VMEM)
    stats_ref, acc_ref,            # scratch: (2, H) running max/norm, (H, D)
    *, ps: int, max_pages: int, scale: float,
):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        stats_ref[0, :] = jnp.full_like(stats_ref[0, :], NEG_INF)  # m
        stats_ref[1, :] = jnp.zeros_like(stats_ref[1, :])          # l
        acc_ref[...] = jnp.zeros_like(acc_ref)

    pos = pos_ref[b]
    valid = jnp.logical_and(p * ps <= pos, table_ref[b, p] >= 0)

    @pl.when(valid)
    def _page():
        q = q_ref[0].astype(jnp.float32) * scale          # (H, D)
        k = k_ref[0].astype(jnp.float32)                  # (ps, Hkv, D)
        v = v_ref[0].astype(jnp.float32)
        h, d = q.shape
        h_kv = k.shape[1]
        g = h // h_kv

        qg = q.reshape(h_kv, g, d)
        kt = k.transpose(1, 0, 2)                         # (Hkv, ps, D)
        s = jax.lax.dot_general(
            qg, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(h, ps)                                  # (H, ps)
        k_pos = p * ps + jax.lax.broadcasted_iota(jnp.int32, (h, ps), 1)
        s = jnp.where(k_pos <= pos, s, NEG_INF)

        m_prev = stats_ref[0, :]
        l_prev = stats_ref[1, :]
        m_new = jnp.maximum(m_prev, s.max(axis=1))
        alpha = jnp.exp(m_prev - m_new)
        # exp(min(s - m, 0)): s <= m by construction, the guard keeps a
        # +inf out of the accumulator if a NaN/overflow sneaks into s
        pexp = jnp.exp(jnp.minimum(s - m_new[:, None], 0.0))
        l_new = l_prev * alpha + pexp.sum(axis=1)
        vt = v.transpose(1, 0, 2)                         # (Hkv, ps, D)
        pg = pexp.reshape(h_kv, g, ps)
        o = jax.lax.dot_general(
            pg, vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(h, d)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + o
        stats_ref[0, :] = m_new
        stats_ref[1, :] = l_new

    @pl.when(p == max_pages - 1)
    def _finalize():
        l = jnp.maximum(stats_ref[1, :], 1e-30)
        o_ref[0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)


def paged_attention(q, k_pages_l, v_pages_l, table, pos, interpret: bool = False):
    """Drop-in for ``kubetpu.jobs.paged._attend_paged``:
    q (B, H, D); pages (P, ps, H_kv, D); table (B, max_pages) int32 with
    -1 for unmapped; pos (B,) query positions. Returns (B, H, D)."""
    b, h, d = q.shape
    n_pool, ps, h_kv, _ = k_pages_l.shape
    max_pages = table.shape[1]
    scale = d ** -0.5

    def page_index(b_i, p_i, table_ref, pos_ref):
        return (jnp.maximum(table_ref[b_i, p_i], 0), 0, 0, 0)

    kernel = functools.partial(
        _paged_attn_kernel, ps=ps, max_pages=max_pages, scale=scale
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_pages),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda b_i, p_i, t, s: (b_i, 0, 0)),
            pl.BlockSpec((1, ps, h_kv, d), page_index),
            pl.BlockSpec((1, ps, h_kv, d), page_index),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda b_i, p_i, t, s: (b_i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, h), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(table, pos, q, k_pages_l, v_pages_l)
