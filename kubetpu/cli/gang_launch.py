"""``kubetpu-gang-launch`` — turn a scheduled gang into a REAL
``jax.distributed`` process group.

The launcher half of the multi-host story (the reference's analog is the
CRI shim starting containers with the device plugin's env injection,
nvidia_gpu_manager.go:216-241; kubetpu's controller returns that env over
the wire). Flow:

1. fetch each gang member's launcher env from the control plane
   (``GET /pods/<name>`` — the same payload a container runtime would
   inject);
2. spawn one ``kubetpu.cli.gang_worker`` OS process per member, rank =
   position in the gang, with that env;
3. wait; verify every worker reports the SAME finite loss — the proof the
   cross-process gradient all-reduce (and therefore the whole env
   contract: coordinator reachability, rank ordering, device visibility)
   works end to end.

Single-machine by design (every worker spawns locally): this is the CI /
smoke path. On a real multi-host slice, run rank i's command on host i —
the printed ``commands`` list is exactly what to run where.

    python -m kubetpu.cli.gang_launch --controller URL [--token T]
        [--platform cpu] [--timeout S] POD [POD ...]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
from typing import Dict, List, Optional

from kubetpu.wire.httpcommon import RetryPolicy, request_json

# env fetches ride the shared retrying client: a transient controller blip
# (reconcile hiccup, restart) costs a backoff, not an aborted launch
FETCH_RETRY = RetryPolicy(attempts=4, base_delay=0.1, max_delay=2.0,
                          deadline=60.0)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _fetch_pod_env(controller: str, pod: str, token: Optional[str]) -> Dict[str, str]:
    """The device-bearing container's injected env for a placed pod.
    Raises (via select_device_env) when no container carries a device
    env — a worker silently launched on default devices would mask the
    env-contract breakage this launcher exists to certify."""
    from kubetpu.jobs.launch import select_device_env

    body = request_json(
        controller.rstrip("/") + f"/pods/{pod}",
        token=token, timeout=30, retry=FETCH_RETRY,
    )
    envs = [
        result.get("env", {}) if isinstance(result, dict) else {}
        for result in body.get("containers", {}).values()
    ]
    return select_device_env(envs)


def launch_gang(
    controller: str,
    pod_names: List[str],
    token: Optional[str] = None,
    platform: Optional[str] = None,
    coordinator_port: Optional[int] = None,
    timeout: float = 240.0,
) -> dict:
    """Spawn one worker process per gang member and collect their reports.

    Returns {"workers": [per-worker report...], "loss": common loss,
    "commands": the argv each rank ran, "trace_id": the launch's trace id
    (fetch the stitched controller+agent timeline at the controller's
    ``GET /trace/<id>``)} — raises RuntimeError when a worker fails or
    the losses disagree (a broken cross-process psum).
    """
    from kubetpu.obs import trace as obs_trace

    with obs_trace.span("gang_launch", component="gang-launch",
                        pods=len(pod_names)) as _root:
        out = _launch_gang_inner(controller, pod_names, token, platform,
                                 coordinator_port, timeout)
        out["trace_id"] = _root.trace_id
        return out


def _launch_gang_inner(controller, pod_names, token, platform,
                       coordinator_port, timeout) -> dict:
    port = coordinator_port or _free_port()
    # fetch EVERY env before spawning anything: a 404 on a later member
    # must not leave earlier workers orphaned at the coordinator barrier
    envs = []
    for pod in pod_names:
        env = dict(os.environ)
        env.update(_fetch_pod_env(controller, pod, token))
        envs.append(env)
    procs = []
    commands: List[List[str]] = []
    reports = []
    errors = []
    try:
        for rank, env in enumerate(envs):
            cmd = [
                sys.executable, "-m", "kubetpu.cli.gang_worker",
                "--coordinator", f"127.0.0.1:{port}",
                "--num-processes", str(len(pod_names)),
                "--rank", str(rank),
            ]
            if platform:
                cmd += ["--platform", platform]
            commands.append(cmd)
            procs.append(subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            ))
        # ONE shared deadline across ranks: a hung coordinator must cost
        # ~timeout total, not timeout x N (the other ranks are blocked on
        # the same barrier and die the moment it is gone)
        import time as _time

        deadline = _time.monotonic() + timeout
        for rank, p in enumerate(procs):
            try:
                out, err = p.communicate(
                    timeout=max(1.0, deadline - _time.monotonic())
                )
            except subprocess.TimeoutExpired:
                p.kill()
                out, err = p.communicate()
                errors.append(f"rank {rank}: timeout (shared {timeout}s deadline)")
                continue
            if p.returncode != 0:
                errors.append(
                    f"rank {rank}: exit {p.returncode}: {err.strip()[-500:]}"
                )
                continue
            lines = [l for l in out.splitlines() if l.startswith("{")]
            if not lines:
                errors.append(f"rank {rank}: exit 0 but no JSON report")
                continue
            reports.append(json.loads(lines[-1]))
    finally:
        for p in procs:  # reap stragglers on any error path
            if p.poll() is None:
                p.kill()
                p.communicate()
    if errors:
        raise RuntimeError("gang launch failed: " + "; ".join(errors))
    losses = sorted({round(r["loss"], 6) for r in reports})
    if len(losses) != 1:
        raise RuntimeError(
            f"workers disagree on the all-reduced loss: {losses} — the "
            "cross-process psum is broken"
        )
    return {
        "workers": sorted(reports, key=lambda r: r["process_index"]),
        "loss": losses[0],
        "commands": commands,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--controller", required=True, help="controller base URL")
    ap.add_argument("--token", default=os.environ.get("KUBETPU_WIRE_TOKEN"))
    ap.add_argument("--platform", default=None,
                    help="worker platform pin ('cpu' = hardware-free)")
    ap.add_argument("--timeout", type=float, default=240.0)
    ap.add_argument("pods", nargs="+", help="gang member pod names, rank order")
    args = ap.parse_args(argv)
    out = launch_gang(
        args.controller, args.pods, token=args.token,
        platform=args.platform, timeout=args.timeout,
    )
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
