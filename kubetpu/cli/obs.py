"""``kubetpu-obs`` — the operator's one-screen fleet summary.

Scrapes the controller's FEDERATED ``/metrics`` (and any extra ``/metrics``
endpoints — agents directly, or serving replicas behind an
``obs.exporter.MetricsServer``) and renders the numbers an operator
actually pages on: nodes by breaker state, free/held chips, pending pods,
scheduler latency percentiles, per-node agent counters, and serving
TTFT/ITL/queue when a serving endpoint is scraped. Scraping a Round-14
``RouterServer`` adds the data-plane section: routed/shed/queued counts,
replica breaker states, last autoscaler action, and per-replica load +
prefix hit rate from the federated ``replica="<name>"`` series.
``--trace ID`` renders one stitched trace as an indented timeline — for
a routed generate that includes the router hop above its replica leg.

    python -m kubetpu.cli.obs [VIEW] --controller URL [--token T]
                              [--scrape URL ...] [--watch SECONDS]
    python -m kubetpu.cli.obs --controller URL --trace TRACE_ID

Round-11 VIEWs over the same endpoints (default ``summary``):

    slo       the declared objectives' judgment surface — SLI value vs
              threshold, fast/slow burn rates, FIRING flags — from each
              target's ``kubetpu_slo_*`` gauges (the controller's are
              fleet-level, a serving exporter's are per-replica)
    profile   the sampled profiler's per-phase step breakdown + per-leg
              jit recompile counters from ``kubetpu_profile_*`` /
              ``kubetpu_jit_*`` (empty unless ``enable_profiler`` ran)
    events    each target's ``GET /events`` structured event log as a
              merged timeline (``--kind`` filters, ``--limit`` tails)

One-shot by default; ``--watch N`` redraws every N seconds until ^C.
Auth: ``KUBETPU_WIRE_TOKEN`` (or ``--token``) rides as the bearer token.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.parse
from typing import Dict, List, Optional, Tuple

from kubetpu.obs.registry import parse_prometheus_text
from kubetpu.wire.httpcommon import NO_RETRY, request_text


def _fetch(url: str, token: Optional[str], timeout: float = 10.0) -> str:
    """One read-only scrape via the shared wire client (Round-12 — raw
    ``urlopen`` is lint-rejected, KTP002). ``NO_RETRY``: a CLI refresh
    beats stale backoff; ``--watch`` will be back in N seconds anyway."""
    return request_text(url, token=token, timeout=timeout, retry=NO_RETRY)


def _index(samples) -> Dict[str, List[Tuple[dict, float]]]:
    out: Dict[str, List[Tuple[dict, float]]] = {}
    for name, labels, value in samples:
        out.setdefault(name, []).append((labels, value))
    return out


def _pick(idx, name: str, **want) -> Optional[float]:
    for labels, value in idx.get(name, []):
        if all(labels.get(k) == v for k, v in want.items()):
            return value
    return None


def _fmt_ms(seconds: Optional[float]) -> str:
    return "-" if seconds is None else f"{seconds * 1e3:.1f}ms"


def render_summary(metrics_text: str, source: str) -> str:
    """One fleet summary block from one exposition text."""
    idx = _index(parse_prometheus_text(metrics_text))
    lines = [f"== {source} =="]

    # replica identification (Round-11 standard process gauges)
    for labels, _v in idx.get("kubetpu_build_info", []):
        up = _pick(idx, "kubetpu_process_uptime_seconds") or 0.0
        rss = _pick(idx, "kubetpu_process_rss_bytes")
        rss_s = f" rss={rss / 1e6:.0f}MB" if rss and rss == rss else ""
        lines.append(f"build     {labels.get('component', '?')} "
                     f"v{labels.get('version', '?')} "
                     f"up={up:.0f}s{rss_s}")
        break

    states = {labels.get("state"): int(v)
              for labels, v in idx.get("kubetpu_nodes", [])}
    if states:
        lines.append("nodes     " + "  ".join(
            f"{s}={states.get(s, 0)}"
            for s in ("healthy", "suspect", "probation")))
    chips = []
    for labels, v in idx.get("kubetpu_chips_free", []):
        dev = labels.get("device", "?")
        held = _pick(idx, "kubetpu_chips_held", device=dev) or 0
        if v or held:
            chips.append(f"{dev}: free={int(v)} held={int(held)}")
    if chips:
        lines.append("chips     " + "  ".join(chips))
    pending = _pick(idx, "kubetpu_pending_pods")
    if pending is not None:
        lines.append(f"pending   {int(pending)} pod(s)")

    # Round-18 vChips: fleet fragmentation from the per-chip occupancy
    # gauges — how many chips carry fractional confetti, how full they
    # are on average, and how many vChip placements were ever made
    occ = [v for _labels, v in idx.get("kubetpu_chip_occupancy_frac", [])]
    if occ:
        partial = [v for v in occ if 0.0 < v < 1.0]
        frac_allocs = _pick(
            idx, "kubetpu_fractional_allocations_total") or 0
        mean = (sum(partial) / len(partial)) if partial else 0.0
        lines.append(
            f"frag      partial_chips={len(partial)}/{len(occ)} "
            f"mean_occ={mean:.2f} frac_allocs={int(frac_allocs)}")

    # scheduler latency summaries: one row per op
    lat = {}
    for labels, v in idx.get("kubetpu_schedule_latency_seconds", []):
        op, q = labels.get("op"), labels.get("quantile")
        if op and q:
            lat.setdefault(op, {})[q] = v
    for op in sorted(lat):
        n = _pick(idx, "kubetpu_schedule_latency_seconds_count", op=op)
        lines.append(
            f"sched     {op}: p50={_fmt_ms(lat[op].get('0.5'))} "
            f"p90={_fmt_ms(lat[op].get('0.9'))} "
            f"p99={_fmt_ms(lat[op].get('0.99'))} "
            f"n={int(n or 0)}")

    # per-node agent counters (federated series carry node=...)
    per_node: Dict[str, Dict[str, int]] = {}
    for short in ("nodeinfo_requests", "allocate_requests",
                  "allocate_replays", "errors"):
        for labels, v in idx.get(f"kubetpu_agent_{short}_total", []):
            node = labels.get("node")
            if node:
                per_node.setdefault(node, {})[short] = int(v)
    for node in sorted(per_node):
        c = per_node[node]
        lines.append(
            f"agent     {node}: nodeinfo={c.get('nodeinfo_requests', 0)} "
            f"allocate={c.get('allocate_requests', 0)} "
            f"replays={c.get('allocate_replays', 0)} "
            f"errors={c.get('errors', 0)}")

    # serving histograms (present when scraping a serving exporter)
    srv = {}
    for labels, v in idx.get("kubetpu_serving_latency_seconds", []):
        op, q = labels.get("op"), labels.get("quantile")
        if op in ("ttft", "itl", "queue_wait") and q in ("0.5", "0.99"):
            srv.setdefault(op, {})[q] = v
    if srv:
        lines.append("serving   " + "  ".join(
            f"{op} p50={_fmt_ms(srv[op].get('0.5'))}/"
            f"p99={_fmt_ms(srv[op].get('0.99'))}"
            for op in ("ttft", "itl", "queue_wait") if op in srv))
        act = _pick(idx, "kubetpu_serving_active_slots")
        depth = _pick(idx, "kubetpu_serving_queue_depth")
        if act is not None or depth is not None:
            lines.append(
                f"serving   active_slots={int(act or 0)} "
                f"queue_depth={int(depth or 0)}")

    # Round-14 router data plane (present when scraping a RouterServer:
    # its own counters plus every replica's series federated under
    # replica="<name>")
    outcomes = {labels.get("outcome"): int(v) for labels, v in
                idx.get("kubetpu_router_requests_total", [])}
    rep_states = {labels.get("state"): int(v) for labels, v in
                  idx.get("kubetpu_router_replicas", [])}
    if outcomes or rep_states:
        burning = _pick(idx, "kubetpu_router_burning")
        lines.append(
            f"router    routed={outcomes.get('routed', 0)} "
            f"shed={outcomes.get('shed', 0)} "
            f"queue_timeout={outcomes.get('queue_timeout', 0)} "
            f"fallbacks={int(_pick(idx, 'kubetpu_router_fallback_total') or 0)} "
            f"queued={int(_pick(idx, 'kubetpu_router_queued_total') or 0)}"
            + ("  BURNING" if burning else ""))
        lines.append("router    replicas " + "  ".join(
            f"{s}={rep_states.get(s, 0)}"
            for s in ("healthy", "suspect", "probation", "dead")))
        ups = _pick(idx, "kubetpu_autoscaler_scale_ups_total")
        downs = _pick(idx, "kubetpu_autoscaler_scale_downs_total")
        if ups is not None or downs is not None:
            last = _pick(idx, "kubetpu_autoscaler_last_scale_ts") or 0.0
            ago = (f" last={time.time() - last:.0f}s ago" if last else "")
            lines.append(f"scale     ups={int(ups or 0)} "
                         f"downs={int(downs or 0)}{ago}")
        # per-replica load + prefix hit rate from the federated series
        per_rep: Dict[str, Dict[str, float]] = {}

        def by_replica(metric, key, **want):
            for labels, v in idx.get(metric, []):
                rep = labels.get("replica")
                if rep and all(labels.get(k) == v2
                               for k, v2 in want.items()):
                    per_rep.setdefault(rep, {})[key] = v

        by_replica("kubetpu_serving_active_slots", "active")
        by_replica("kubetpu_serving_queue_depth", "queue")
        by_replica("kubetpu_serving_pages_free", "pages_free")
        by_replica("kubetpu_prefix_requests_total", "hits", result="hit")
        by_replica("kubetpu_prefix_requests_total", "misses",
                   result="miss")
        for rep in sorted(per_rep):
            c = per_rep[rep]
            total = c.get("hits", 0) + c.get("misses", 0)
            hit_s = (f" hit_rate={c.get('hits', 0) / total:.2f}"
                     if total else "")
            pages = c.get("pages_free")
            pages_s = (f" pages_free={int(pages)}"
                       if pages is not None else "")
            lines.append(
                f"replica   {rep}: active={int(c.get('active', 0))} "
                f"queue={int(c.get('queue', 0))}{pages_s}{hit_s}")

    # Round-17 disaggregated prefill/decode (present when any replica
    # advertises a role / ships handoffs): per-role replica counts, the
    # in-flight + per-outcome handoff ledger, and the pipelining proof
    # (fraction of KV bytes shipped before prefill finished)
    role_counts: Dict[str, int] = {}
    for labels, v in idx.get("kubetpu_serving_role", []):
        role = labels.get("role")
        if role and v:
            role_counts[role] = role_counts.get(role, 0) + 1
    # SUM per outcome: the federated scrape carries one series per
    # prefill replica (replica="..."), and a dict comprehension would
    # keep whichever replica iterates last
    handoffs: Dict[str, int] = {}
    for labels, v in idx.get("kubetpu_handoffs_total", []):
        result = labels.get("result")
        if result:
            handoffs[result] = handoffs.get(result, 0) + int(v)
    if role_counts or handoffs:
        inflight = sum(v for _labels, v in
                       idx.get("kubetpu_handoffs_inflight", []))
        streamed = sum(v for _labels, v in
                       idx.get("kubetpu_handoff_pages_streamed_total", []))
        overlap = max((v for _labels, v in
                       idx.get("kubetpu_handoff_overlap_frac", [])),
                      default=0.0)
        lines.append(
            "disagg    roles " + "  ".join(
                f"{r}={role_counts.get(r, 0)}"
                for r in ("prefill", "decode", "both")))
        lines.append(
            f"disagg    handoffs inflight={int(inflight)} "
            f"committed={handoffs.get('committed', 0)} "
            f"aborted={handoffs.get('aborted', 0)} "
            f"refused={handoffs.get('refused', 0)} "
            f"ambiguous={handoffs.get('ambiguous', 0)}  "
            f"pages_streamed={int(streamed)} "
            f"overlap={overlap:.2f}")

    # Round-19 tiered KV cache (present when any scraped replica has a
    # host tier): per-tier admission hits summed across the fleet, host
    # spill/fill traffic, resident host bytes, and the peer-fetch ledger
    tier_hits: Dict[str, int] = {}
    for labels, v in idx.get("kubetpu_prefix_tier_hits_total", []):
        tier = labels.get("tier")
        if tier:
            tier_hits[tier] = tier_hits.get(tier, 0) + int(v)
    if tier_hits:
        spills = sum(int(v) for _labels, v in
                     idx.get("kubetpu_prefix_tier_spills_total", []))
        fills: Dict[str, int] = {}
        for labels, v in idx.get("kubetpu_prefix_tier_fills_total", []):
            tier = labels.get("tier")
            if tier:
                fills[tier] = fills.get(tier, 0) + int(v)
        host_bytes = sum(v for _labels, v in
                         idx.get("kubetpu_prefix_host_bytes", []))
        fetches = {labels.get("result"): int(v) for labels, v in
                   idx.get("kubetpu_peer_prefix_fetch_total", [])}
        lines.append(
            "tiering   hits " + "  ".join(
                f"{t}={tier_hits.get(t, 0)}"
                for t in ("hbm", "host", "peer"))
            + f"  spills={spills} "
            f"fills host={fills.get('host', 0)} peer={fills.get('peer', 0)} "
            f"host_bytes={host_bytes / 1e6:.1f}MB")
        if fetches:
            lines.append(
                f"tiering   peer_fetch hit={fetches.get('hit', 0)} "
                f"miss={fetches.get('miss', 0)} "
                f"degraded={fetches.get('degraded', 0)}")

    # Round-22 multi-LoRA tenants (present when any scraped replica
    # serves the stacked-adapter path): per-adapter request/token
    # traffic summed across the fleet (the exporter already bounds
    # cardinality to top-K + the overflow bucket), and the residency
    # gauges behind tenant-affine routing
    tenants: Dict[str, Dict[str, int]] = {}

    def by_adapter(metric, key):
        for labels, v in idx.get(metric, []):
            adapter = labels.get("adapter")
            if adapter:
                t = tenants.setdefault(adapter, {})
                t[key] = t.get(key, 0) + int(v)

    by_adapter("kubetpu_tenant_requests_total", "req")
    by_adapter("kubetpu_tenant_decode_tokens_total", "tok")
    by_adapter("kubetpu_tenant_prefill_tokens_saved_total", "saved")
    if tenants or idx.get("kubetpu_adapter_capacity"):
        resident = sum(int(v) for _labels, v in
                       idx.get("kubetpu_adapters_resident", []))
        capacity = sum(int(v) for _labels, v in
                       idx.get("kubetpu_adapter_capacity", []))
        loads = sum(int(v) for _labels, v in
                    idx.get("kubetpu_adapter_loads_total", []))
        evicts = sum(int(v) for _labels, v in
                     idx.get("kubetpu_adapter_evicts_total", []))
        lines.append(
            f"tenants   adapters={len(tenants)} "
            f"resident={resident}/{capacity} "
            f"loads={loads} evicts={evicts}  "
            f"requests={sum(t.get('req', 0) for t in tenants.values())} "
            f"tokens={sum(t.get('tok', 0) for t in tenants.values())} "
            f"saved={sum(t.get('saved', 0) for t in tenants.values())}")
        top = sorted(tenants, key=lambda a: -tenants[a].get("tok", 0))[:5]
        for adapter in top:
            t = tenants[adapter]
            lines.append(
                f"tenants   {adapter}: req={t.get('req', 0)} "
                f"tok={t.get('tok', 0)} saved={t.get('saved', 0)}")

    # Round-20 crash tolerance (present when the controller journals /
    # the router saw a restart): journal volume and compaction state,
    # the last cold-restart replay, the reconciliation diff, and the
    # serving-side restart/takeover ledger
    def _one(name: str, default=None):
        vals = [v for _labels, v in idx.get(name, [])]
        return vals[0] if vals else default

    if idx.get("kubetpu_journal_seq"):
        recovering = _one("kubetpu_controller_recovering", 0.0)
        state = "RECOVERING" if recovering else "ready"
        lines.append(
            f"journal   seq={int(_one('kubetpu_journal_seq', 0))} "
            f"wal={_one('kubetpu_journal_wal_bytes', 0) / 1e3:.1f}KB "
            f"records={int(_one('kubetpu_journal_records_appended', 0))} "
            f"snapshots={int(_one('kubetpu_journal_snapshots', 0))} "
            f"torn_tails={int(_one('kubetpu_journal_torn_tails', 0))}  "
            f"[{state}]")
        replays = int(_one("kubetpu_recovery_replays_total", 0))
        if replays:
            lines.append(
                f"recovery  replays={replays} "
                f"last_replay="
                f"{_one('kubetpu_recovery_last_replay_seconds', 0):.3f}s "
                f"restored="
                f"{int(_one('kubetpu_recovery_placements_restored_total', 0))} "
                f"ghosts="
                f"{int(_one('kubetpu_recovery_ghosts_repended_total', 0))} "
                f"orphans_freed="
                f"{int(_one('kubetpu_recovery_orphans_freed_total', 0))} "
                f"agents_unreachable="
                f"{int(_one('kubetpu_recovery_agents_unreachable_total', 0))}")
    restarts = int(_one("kubetpu_router_replica_restarts_total", 0))
    takeovers = int(_one("kubetpu_router_replica_takeovers_total", 0))
    if restarts or takeovers:
        lines.append(
            f"recovery  replica_restarts={restarts} "
            f"takeovers={takeovers} "
            f"pins_dropped="
            f"{int(_one('kubetpu_router_restart_unpins_total', 0))}")
    return "\n".join(lines)


def render_slo(metrics_text: str, source: str) -> str:
    """The SLO judgment surface from one exposition text's
    ``kubetpu_slo_*`` gauges — one row per objective: SLI value vs
    threshold, OK bit, fast/slow burn rates, FIRING flag. This is the
    view an operator (or the autoscaler, programmatically) reads to
    answer "is the fleet inside its objectives, and how fast is the
    budget burning"."""
    idx = _index(parse_prometheus_text(metrics_text))
    lines = [f"== {source} =="]
    slos: Dict[str, dict] = {}
    for short in ("value", "threshold", "ok", "firing", "data"):
        for labels, v in idx.get(f"kubetpu_slo_{short}", []):
            name = labels.get("slo")
            if name:
                slos.setdefault(name, {})[short] = v
    for labels, v in idx.get("kubetpu_slo_burn_rate", []):
        name, window = labels.get("slo"), labels.get("window")
        if name and window:
            slos.setdefault(name, {})[f"burn_{window}"] = v
    if not slos:
        lines.append("no kubetpu_slo_* series (no objectives declared?)")
        return "\n".join(lines)
    for name in sorted(slos):
        s = slos[name]
        ok = s.get("ok")
        # data==0: the SLI went absent — value/ok are the LAST definite
        # verdict, not the current state; never let stale gauges read as
        # fresh health
        stale = s.get("data") == 0.0
        if stale:
            state, value = "no data", None
        else:
            state = ("FIRING" if s.get("firing") else
                     "ok" if ok else "-" if ok is None else "violating")
            value = s.get("value")
        lines.append(
            f"slo       {name}: "
            f"value={'-' if value is None else f'{value:.4g}'} "
            f"threshold={s.get('threshold', float('nan')):.4g} "
            f"burn fast={s.get('burn_fast', 0.0):.2f} "
            f"slow={s.get('burn_slow', 0.0):.2f}  {state}")
    return "\n".join(lines)


def render_profile(metrics_text: str, source: str) -> str:
    """The sampled profiler's breakdown from one exposition text:
    where a step's milliseconds go (per-phase seconds + share of sampled
    wall) and what compiled when (per-leg recompile count + compile
    seconds). Empty unless the replica ran ``enable_profiler``."""
    idx = _index(parse_prometheus_text(metrics_text))
    lines = [f"== {source} =="]
    sampled = _pick(idx, "kubetpu_profile_sampled_steps_total")
    wall = _pick(idx, "kubetpu_profile_step_seconds_total")
    if sampled:
        lines.append(f"profile   sampled_steps={int(sampled)} "
                     f"wall={wall or 0.0:.3f}s")
        for labels, v in sorted(
                idx.get("kubetpu_profile_phase_seconds_total", []),
                key=lambda lv: lv[0].get("phase", "")):
            frac = v / wall if wall else 0.0
            lines.append(f"phase     {labels.get('phase', '?')}: "
                         f"{v:.3f}s ({frac:.0%})")
    legs = {}
    for labels, v in idx.get("kubetpu_jit_recompiles_total", []):
        legs.setdefault(labels.get("leg", "?"), {})["n"] = v
    for labels, v in idx.get("kubetpu_jit_compile_seconds_total", []):
        legs.setdefault(labels.get("leg", "?"), {})["s"] = v
    for leg in sorted(legs):
        lines.append(f"compile   {leg}: recompiles="
                     f"{int(legs[leg].get('n', 0))} "
                     f"{legs[leg].get('s', 0.0):.3f}s")
    if len(lines) == 1:
        lines.append("no profiler series (enable_profiler not called?)")
    return "\n".join(lines)


def render_events(jsonl: str, source: str) -> str:
    """One ``GET /events`` JSONL body as a human timeline: local time,
    kind, component, the free-form fields, and a short trace-id link
    when the event was raised inside a span."""
    lines = [f"== {source} =="]
    for raw in jsonl.splitlines():
        if not raw.strip():
            continue
        try:
            ev = json.loads(raw)
        except ValueError:
            lines.append(f"  (unparseable: {raw[:60]!r})")
            continue
        ts = time.strftime("%H:%M:%S", time.localtime(ev.get("ts", 0)))
        comp = ev.get("component", "")
        rest = "  ".join(
            f"{k}={v}" for k, v in ev.items()
            if k not in ("ts", "seq", "kind", "component", "trace_id"))
        tid = ev.get("trace_id")
        link = f"  trace={tid[:8]}" if tid else ""
        lines.append(f"{ts}  {ev.get('kind', '?'):<16} "
                     f"{comp:<12} {rest}{link}".rstrip())
    if len(lines) == 1:
        lines.append("no events")
    return "\n".join(lines)


def render_trace(body: dict) -> str:
    """Indented span timeline of one stitched trace (children under
    parents, siblings by start time; orphaned parents render at root —
    a dark agent loses its leg, not the whole view)."""
    spans = body.get("spans", [])
    by_id = {s["span_id"]: s for s in spans}
    children: Dict[Optional[str], List[dict]] = {}
    for s in spans:
        parent = s.get("parent_id")
        children.setdefault(
            parent if parent in by_id else None, []).append(s)
    lines = [f"trace {body.get('trace', '?')} ({len(spans)} spans)"]

    def walk(parent_key, depth):
        for s in sorted(children.get(parent_key, []),
                        key=lambda x: x["start"]):
            comp = s.get("component", "")
            tag = f" [{comp}]" if comp else ""
            status = "" if s.get("status") == "ok" else f" !{s.get('status')}"
            lines.append(
                f"{'  ' * depth}- {s['op']}{tag} "
                f"{s.get('dur', 0) * 1e3:.2f}ms{status}")
            walk(s["span_id"], depth + 1)

    walk(None, 1)
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubetpu-obs", description=__doc__)
    ap.add_argument("view", nargs="?", default="summary",
                    choices=("summary", "slo", "profile", "events"),
                    help="what to render from the scraped targets "
                         "(default: the fleet summary)")
    ap.add_argument("--kind", default=None,
                    help="events view: only this event kind")
    ap.add_argument("--limit", type=int, default=None,
                    help="events view: last N events per target")
    ap.add_argument("--controller", default=None,
                    help="controller base URL (its /metrics is already "
                         "fleet-federated)")
    ap.add_argument("--scrape", nargs="*", default=[], metavar="URL",
                    help="extra /metrics base URLs (agents, serving "
                         "exporters)")
    ap.add_argument("--token", default=os.environ.get("KUBETPU_WIRE_TOKEN"))
    ap.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="redraw every N seconds (0 = one-shot)")
    ap.add_argument("--trace", default=None, metavar="TRACE_ID",
                    help="render one stitched trace from the controller "
                         "and exit")
    args = ap.parse_args(argv)
    if not args.controller and not args.scrape:
        ap.error("need --controller and/or --scrape URLs")

    if args.trace:
        if not args.controller:
            ap.error("--trace needs --controller")
        body = json.loads(_fetch(
            args.controller.rstrip("/") + f"/trace/{args.trace}",
            args.token))
        print(render_trace(body))
        return 0

    targets = []
    if args.controller:
        targets.append(("controller", args.controller.rstrip("/")))
    targets.extend(("scrape", u.rstrip("/")) for u in args.scrape)

    renderers = {"summary": render_summary, "slo": render_slo,
                 "profile": render_profile}
    while True:
        blocks = []
        for kind, base in targets:
            try:
                if args.view == "events":
                    q = {}
                    if args.kind:
                        q["kind"] = args.kind
                    if args.limit is not None:
                        q["limit"] = args.limit
                    url = base + "/events" + (
                        "?" + urllib.parse.urlencode(q) if q else "")
                    body = _fetch(url, args.token)
                    blocks.append(render_events(body, f"{kind} {base}"))
                else:
                    text = _fetch(base + "/metrics", args.token)
                    blocks.append(
                        renderers[args.view](text, f"{kind} {base}"))
            except Exception as e:  # noqa: BLE001 — show the gap, keep going
                blocks.append(f"== {kind} {base} ==\nUNREACHABLE: {e}")
        if args.watch:
            sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
        print("\n\n".join(blocks), flush=True)
        if not args.watch:
            return 0
        time.sleep(args.watch)


if __name__ == "__main__":
    sys.exit(main())
