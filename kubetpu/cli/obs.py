"""``kubetpu-obs`` — the operator's one-screen fleet summary.

Scrapes the controller's FEDERATED ``/metrics`` (and any extra ``/metrics``
endpoints — agents directly, or serving replicas behind an
``obs.exporter.MetricsServer``) and renders the numbers an operator
actually pages on: nodes by breaker state, free/held chips, pending pods,
scheduler latency percentiles, per-node agent counters, and serving
TTFT/ITL/queue when a serving endpoint is scraped. ``--trace ID`` renders
one stitched trace as an indented timeline instead.

    python -m kubetpu.cli.obs --controller URL [--token T]
                              [--scrape URL ...] [--watch SECONDS]
    python -m kubetpu.cli.obs --controller URL --trace TRACE_ID

One-shot by default; ``--watch N`` redraws every N seconds until ^C.
Auth: ``KUBETPU_WIRE_TOKEN`` (or ``--token``) rides as the bearer token.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

from kubetpu.obs.registry import parse_prometheus_text


def _fetch(url: str, token: Optional[str], timeout: float = 10.0) -> bytes:
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    req = urllib.request.Request(url, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.read()


def _index(samples) -> Dict[str, List[Tuple[dict, float]]]:
    out: Dict[str, List[Tuple[dict, float]]] = {}
    for name, labels, value in samples:
        out.setdefault(name, []).append((labels, value))
    return out


def _pick(idx, name: str, **want) -> Optional[float]:
    for labels, value in idx.get(name, []):
        if all(labels.get(k) == v for k, v in want.items()):
            return value
    return None


def _fmt_ms(seconds: Optional[float]) -> str:
    return "-" if seconds is None else f"{seconds * 1e3:.1f}ms"


def render_summary(metrics_text: str, source: str) -> str:
    """One fleet summary block from one exposition text."""
    idx = _index(parse_prometheus_text(metrics_text))
    lines = [f"== {source} =="]

    states = {labels.get("state"): int(v)
              for labels, v in idx.get("kubetpu_nodes", [])}
    if states:
        lines.append("nodes     " + "  ".join(
            f"{s}={states.get(s, 0)}"
            for s in ("healthy", "suspect", "probation")))
    chips = []
    for labels, v in idx.get("kubetpu_chips_free", []):
        dev = labels.get("device", "?")
        held = _pick(idx, "kubetpu_chips_held", device=dev) or 0
        if v or held:
            chips.append(f"{dev}: free={int(v)} held={int(held)}")
    if chips:
        lines.append("chips     " + "  ".join(chips))
    pending = _pick(idx, "kubetpu_pending_pods")
    if pending is not None:
        lines.append(f"pending   {int(pending)} pod(s)")

    # scheduler latency summaries: one row per op
    lat = {}
    for labels, v in idx.get("kubetpu_schedule_latency_seconds", []):
        op, q = labels.get("op"), labels.get("quantile")
        if op and q:
            lat.setdefault(op, {})[q] = v
    for op in sorted(lat):
        n = _pick(idx, "kubetpu_schedule_latency_seconds_count", op=op)
        lines.append(
            f"sched     {op}: p50={_fmt_ms(lat[op].get('0.5'))} "
            f"p90={_fmt_ms(lat[op].get('0.9'))} "
            f"p99={_fmt_ms(lat[op].get('0.99'))} "
            f"n={int(n or 0)}")

    # per-node agent counters (federated series carry node=...)
    per_node: Dict[str, Dict[str, int]] = {}
    for short in ("nodeinfo_requests", "allocate_requests",
                  "allocate_replays", "errors"):
        for labels, v in idx.get(f"kubetpu_agent_{short}_total", []):
            node = labels.get("node")
            if node:
                per_node.setdefault(node, {})[short] = int(v)
    for node in sorted(per_node):
        c = per_node[node]
        lines.append(
            f"agent     {node}: nodeinfo={c.get('nodeinfo_requests', 0)} "
            f"allocate={c.get('allocate_requests', 0)} "
            f"replays={c.get('allocate_replays', 0)} "
            f"errors={c.get('errors', 0)}")

    # serving histograms (present when scraping a serving exporter)
    srv = {}
    for labels, v in idx.get("kubetpu_serving_latency_seconds", []):
        op, q = labels.get("op"), labels.get("quantile")
        if op in ("ttft", "itl", "queue_wait") and q in ("0.5", "0.99"):
            srv.setdefault(op, {})[q] = v
    if srv:
        lines.append("serving   " + "  ".join(
            f"{op} p50={_fmt_ms(srv[op].get('0.5'))}/"
            f"p99={_fmt_ms(srv[op].get('0.99'))}"
            for op in ("ttft", "itl", "queue_wait") if op in srv))
        act = _pick(idx, "kubetpu_serving_active_slots")
        depth = _pick(idx, "kubetpu_serving_queue_depth")
        if act is not None or depth is not None:
            lines.append(
                f"serving   active_slots={int(act or 0)} "
                f"queue_depth={int(depth or 0)}")
    return "\n".join(lines)


def render_trace(body: dict) -> str:
    """Indented span timeline of one stitched trace (children under
    parents, siblings by start time; orphaned parents render at root —
    a dark agent loses its leg, not the whole view)."""
    spans = body.get("spans", [])
    by_id = {s["span_id"]: s for s in spans}
    children: Dict[Optional[str], List[dict]] = {}
    for s in spans:
        parent = s.get("parent_id")
        children.setdefault(
            parent if parent in by_id else None, []).append(s)
    lines = [f"trace {body.get('trace', '?')} ({len(spans)} spans)"]

    def walk(parent_key, depth):
        for s in sorted(children.get(parent_key, []),
                        key=lambda x: x["start"]):
            comp = s.get("component", "")
            tag = f" [{comp}]" if comp else ""
            status = "" if s.get("status") == "ok" else f" !{s.get('status')}"
            lines.append(
                f"{'  ' * depth}- {s['op']}{tag} "
                f"{s.get('dur', 0) * 1e3:.2f}ms{status}")
            walk(s["span_id"], depth + 1)

    walk(None, 1)
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubetpu-obs", description=__doc__)
    ap.add_argument("--controller", default=None,
                    help="controller base URL (its /metrics is already "
                         "fleet-federated)")
    ap.add_argument("--scrape", nargs="*", default=[], metavar="URL",
                    help="extra /metrics base URLs (agents, serving "
                         "exporters)")
    ap.add_argument("--token", default=os.environ.get("KUBETPU_WIRE_TOKEN"))
    ap.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="redraw every N seconds (0 = one-shot)")
    ap.add_argument("--trace", default=None, metavar="TRACE_ID",
                    help="render one stitched trace from the controller "
                         "and exit")
    args = ap.parse_args(argv)
    if not args.controller and not args.scrape:
        ap.error("need --controller and/or --scrape URLs")

    if args.trace:
        if not args.controller:
            ap.error("--trace needs --controller")
        body = json.loads(_fetch(
            args.controller.rstrip("/") + f"/trace/{args.trace}",
            args.token))
        print(render_trace(body))
        return 0

    targets = []
    if args.controller:
        targets.append(("controller", args.controller.rstrip("/")))
    targets.extend(("scrape", u.rstrip("/")) for u in args.scrape)

    while True:
        blocks = []
        for kind, base in targets:
            try:
                text = _fetch(base + "/metrics", args.token).decode()
                blocks.append(render_summary(text, f"{kind} {base}"))
            except Exception as e:  # noqa: BLE001 — show the gap, keep going
                blocks.append(f"== {kind} {base} ==\nUNREACHABLE: {e}")
        if args.watch:
            sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
        print("\n\n".join(blocks), flush=True)
        if not args.watch:
            return 0
        time.sleep(args.watch)


if __name__ == "__main__":
    sys.exit(main())
