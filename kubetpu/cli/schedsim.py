"""``schedsim`` — the benchmark driver over the BASELINE evaluation configs.

Runs each of the five BASELINE.md configurations in fake-device mode against
the real scheduling stack and prints per-config results (placement, latency
percentiles, ICI-contiguity) as JSON lines. ``bench.py`` at the repo root is
the single-headline-number version of config 4 scaled to v5e-256.

    python -m kubetpu.cli.schedsim [--config N] [--rounds R]
"""

from __future__ import annotations

import argparse
import gc
import json
import sys
import time

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.core import Cluster, SchedulingError
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.plugintypes import ResourceGPU, ResourceTPU


def _tpu_pod(name, chips, **extra_requests):
    return PodInfo(name=name, requests=dict(extra_requests),
                   running_containers={"main": ContainerInfo(requests={ResourceTPU: chips})})


def _v5e8_cluster():
    c = Cluster()
    c.register_node("v5e8-n0", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8")))
    return c


def config1():
    """single-pod 1-device request (fake-device mode)"""
    c = _v5e8_cluster()
    t0 = time.perf_counter()
    placed = c.schedule(_tpu_pod("p", 1))
    ms = (time.perf_counter() - t0) * 1e3
    return {"placed": placed.node_name, "latency_ms": round(ms, 3)}


def config2():
    """single-pod 4-chip, ICI-contiguous on one v5e-8 host"""
    c = _v5e8_cluster()
    placed = c.schedule(_tpu_pod("quad", 4))
    _, _, env = c.allocate("quad")["main"]
    return {
        "placed": placed.node_name,
        "bounds": env["TPU_CHIPS_PER_PROCESS_BOUNDS"],
        "contiguity": c.gang_contiguity([placed]),
    }


def config3():
    """multi-pod bin-packing on one v5e-8 host (mixed 1/2/4-chip pods)"""
    c = _v5e8_cluster()
    sizes = [4, 2, 1, 1]
    for i, n in enumerate(sizes):
        c.schedule(_tpu_pod(f"p{i}", n))
    free = c.nodes["v5e8-n0"].info.allocatable[ResourceTPU]
    return {"pods": len(sizes), "free_after": free, "packed": free == 0}


def config4(rounds=None):
    """gang-scheduled multi-host job (v5e-64, 8 hosts, all-or-nothing)"""
    rounds = rounds or 5
    c = Cluster()
    for h in range(8):
        c.register_node(
            f"h{h}", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-64", host_index=h))
        )
    lat = []
    contig = None
    for r in range(rounds):
        pods = [_tpu_pod(f"r{r}w{i}", 8) for i in range(8)]
        t0 = time.perf_counter()
        placed = c.schedule_gang(pods)
        lat.append((time.perf_counter() - t0) * 1e3)
        contig = c.gang_contiguity(placed)
        for p in placed:
            c.release(p.name)
    lat.sort()
    return {
        "gang_p50_ms": round(lat[len(lat) // 2], 3),
        "contiguity": contig,
        "all_or_nothing": _rollback_clean(c),
    }


def _rollback_clean(c: Cluster) -> bool:
    pods = [_tpu_pod(f"x{i}", 8) for i in range(9)]  # 9 > 8 hosts
    try:
        c.schedule_gang(pods)
        return False
    except SchedulingError:
        pass
    return all(
        n.info.allocatable[ResourceTPU] == 8 and not n.pods for n in c.nodes.values()
    )


def config5():
    """heterogeneous cluster: mixed NVIDIA-GPU + TPU nodes"""
    from kubetpu.device.nvidia import new_fake_nvidia_gpu_manager
    from kubetpu.device.nvidia.types import (
        GpuInfo, GpusInfo, MemoryInfo, PciInfo, TopologyInfo, VersionInfo,
    )

    bus = [f"0000:{i:02X}:00.0" for i in range(8)]
    gpus = []
    for i in range(8):
        socket = i // 4
        topo = [
            TopologyInfo(bus_id=bus[j], link=5 if j // 2 == i // 2 else 3)
            for j in range(socket * 4, socket * 4 + 4)
            if j != i
        ]
        gpus.append(GpuInfo(id=f"GPU{i:02d}", model="Fake", path=f"/dev/nvidia{i}",
                            memory=MemoryInfo(global_mib=12238),
                            pci=PciInfo(bus_id=bus[i], bandwidth=15760), topology=topo))
    info = GpusInfo(version=VersionInfo(driver="fake", cuda=""), gpus=gpus)

    c = Cluster()
    c.register_node("tpu-node", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8")))
    c.register_node("gpu-node", device=new_fake_nvidia_gpu_manager(info, "v", "d"))
    t = c.schedule(_tpu_pod("tjob", 4))
    g = c.schedule(PodInfo(name="gjob",
                           running_containers={"main": ContainerInfo(requests={ResourceGPU: 4})}))
    return {
        "tpu_pod_on": t.node_name,
        "gpu_pod_on": g.node_name,
        "co_scheduled": t.node_name != g.node_name,
    }


def config6():
    """extension: priority preemption (high evicts low, feasibility-checked)"""
    from kubetpu.core.cluster import PriorityKey

    c = _v5e8_cluster()
    c.schedule(_tpu_pod("low-a", 4))
    c.schedule(_tpu_pod("low-b", 4))
    high = _tpu_pod("high", 4)
    high.requests[PriorityKey] = 10
    placed, evicted = c.schedule_preempting(high)
    return {
        "placed": placed.node_name,
        "evicted": [p.name for p in evicted],
        "preempted": len(evicted) == 1,
    }


def config7():
    """extension: defragmentation (migrations open a perfect block)"""
    c = Cluster()
    for i in range(2):
        c.register_node(
            f"n{i}", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
        )
    # fragment n0: 8 singles, release all but two awkward chips
    placed = {}
    for i in range(8):
        p = c.schedule(_tpu_pod(f"s{i}", 1), lambda n: n == "n0")
        _t, coords = c.pod_chip_coords(p)
        placed[coords[0]] = p.name
    for coord, pname in placed.items():
        if coord not in {(0, 1), (1, 2)}:
            c.release(pname)
    # partially fill n1 so no perfect 6-block exists anywhere without moving
    c.schedule(_tpu_pod("n1pod", 4), lambda n: n == "n1")
    plan = c.defrag_plan(6)
    if plan is None:
        return {"plan": None, "defragged": False}
    if plan == []:
        return {"plan": [], "defragged": True, "note": "already fits"}
    moved, pending = c.execute_defrag(plan, pending=_tpu_pod("big6", 6))
    return {
        "plan": [f"{m.pod_name}:{m.from_node}->{m.to_node}" for m in plan],
        "pending_contiguity": c.gang_contiguity([pending]),
        "defragged": c.gang_contiguity([pending]) == 1.0,
    }


# -- adversarial configs (VERDICT r1 #4): p50 AND p99 under fragmentation, --
# -- churn, and multi-slice scale — the happy-path bench.py number alone   --
# -- says nothing about where the cache design breaks.                     --


def _percentiles(lat_ms):
    lat = sorted(lat_ms)

    def pct(p):
        return round(lat[min(len(lat) - 1, int(round(p / 100 * (len(lat) - 1))))], 3)

    return {"n": len(lat), "p50_ms": pct(50), "p99_ms": pct(99)}


def _v5e256_cluster(slice_uid="slice0", prefix="h"):
    c = Cluster()
    for h in range(32):
        c.register_node(
            f"{prefix}{h:02d}",
            device=new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-256", host_index=h, slice_uid=slice_uid)
            ),
        )
    return c


def config8(rounds=None):
    """adversarial: fragmented v5e-256 (~30% of chips held at random); p50/p99 of mixed placements"""
    import random

    rounds = rounds or 80
    rng = random.Random(42)
    c = _v5e256_cluster()
    # hold a random ~30% of all 256 chips as 1-chip pods: schedule all 256
    # singles, then release a random 70%
    singles = []
    for h in range(32):
        for i in range(8):
            p = c.schedule(_tpu_pod(f"hold-{h}-{i}", 1), lambda n, hh=f"h{h:02d}": n == hh)
            singles.append(p.name)
    rng.shuffle(singles)
    held = singles[: int(len(singles) * 0.30)]
    for name in singles[len(held):]:
        c.release(name)

    lat, failures, window = [], 0, []
    sizes = [1, 2, 4, 8]
    for r in range(rounds):
        size = sizes[r % len(sizes)]
        t0 = time.perf_counter()
        try:
            p = c.schedule(_tpu_pod(f"q{r}", size))
            window.append(p.name)
        except SchedulingError:
            failures += 1
        lat.append((time.perf_counter() - t0) * 1e3)
        if len(window) > 6:  # sliding window keeps pressure without filling up
            c.release(window.pop(0))
    return {**_percentiles(lat), "held_chips": len(held), "failures": failures}


def config9(rounds=None):
    """adversarial: mixed 1/2/4/8-chip pod churn with releases on v5e-256 at ~70% utilization"""
    import random

    rounds = rounds or 300
    rng = random.Random(7)
    c = _v5e256_cluster()
    live = {}  # pod name -> chips
    held = 0
    lat, failures = [], 0
    for i in range(rounds):
        size = rng.choice([1, 1, 2, 2, 4, 8])
        t0 = time.perf_counter()
        try:
            c.schedule(_tpu_pod(f"c{i}", size))
            live[f"c{i}"] = size
            held += size
        except SchedulingError:
            failures += 1
        lat.append((time.perf_counter() - t0) * 1e3)
        while held > 0.75 * 256:  # drain to ~60% so churn continues
            victim = rng.choice(sorted(live))
            held -= live.pop(victim)
            c.release(victim)
    return {**_percentiles(lat), "failures": failures, "final_util": round(held / 256, 2)}


def config10(rounds=None):
    """adversarial: 512-node cluster (16 distinct v5e-256 slices); p50/p99 single-pod + 32-host gang"""
    rounds = rounds or 30
    t0 = time.perf_counter()
    c = Cluster()
    for s in range(16):
        for h in range(32):
            c.register_node(
                f"s{s:02d}h{h:02d}",
                device=new_fake_tpu_dev_manager(
                    make_fake_tpus_info("v5e-256", host_index=h, slice_uid=f"slice{s}")
                ),
            )
    setup_s = time.perf_counter() - t0

    pod_lat = []
    for r in range(rounds):
        t0 = time.perf_counter()
        p = c.schedule(_tpu_pod(f"p{r}", 8))
        pod_lat.append((time.perf_counter() - t0) * 1e3)
        c.release(p.name)
    gang_lat = []
    for r in range(max(3, rounds // 10)):
        pods = [_tpu_pod(f"g{r}w{i}", 8) for i in range(32)]
        t0 = time.perf_counter()
        placed = c.schedule_gang(pods)
        gang_lat.append((time.perf_counter() - t0) * 1e3)
        contig = c.gang_contiguity(placed)
        for p in placed:
            c.release(p.name)
    return {
        "nodes": 512,
        "setup_s": round(setup_s, 2),
        "pod": _percentiles(pod_lat),
        "gang_256chip": _percentiles(gang_lat),
        "gang_contiguity": contig,
    }


def config11(rounds=None):
    """adversarial: controller API end-to-end (HTTP submit -> schedule -> wire allocate -> HTTP release) p50/p99 over live agent servers"""
    import urllib.error
    import uuid

    from kubetpu.wire import NodeAgentServer
    from kubetpu.wire.controller import ControllerServer, pod_to_json
    from kubetpu.wire.httpcommon import request_json

    rounds = rounds or 60
    agents = [
        NodeAgentServer(
            new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-64", host_index=h)
            ),
            f"h{h}",
        )
        for h in range(4)
    ]
    for a in agents:
        a.start()
    controller = ControllerServer(poll_interval=3600)
    controller.start()

    def post(path, obj):
        # the shared retrying client; the idempotency key makes the POST
        # safely retriable (a replayed /pods submit cannot double-place)
        return request_json(controller.address + path, obj, timeout=30,
                            idempotency_key=uuid.uuid4().hex)

    def delete(path):
        try:
            request_json(controller.address + path, method="DELETE",
                         timeout=30)
        except urllib.error.HTTPError as e:
            # a 404 on a DELETE retry means the FIRST attempt succeeded
            # and its response was lost — deleted either way
            if e.code != 404:
                raise

    try:
        for a in agents:
            post("/nodes", {"url": a.address})
        lat = []
        for r in range(rounds):
            pod = pod_to_json(_tpu_pod(f"p{r}", 4))
            t0 = time.perf_counter()
            post("/pods", {"pod": pod})
            lat.append((time.perf_counter() - t0) * 1e3)
            delete(f"/pods/p{r}")
        gang_lat = []
        for r in range(max(3, rounds // 10)):
            gang = [pod_to_json(_tpu_pod(f"g{r}w{i}", 8)) for i in range(4)]
            t0 = time.perf_counter()
            out = post("/pods", {"gang": gang})
            gang_lat.append((time.perf_counter() - t0) * 1e3)
            contig = out["gang_contiguity"]
            for i in range(4):
                delete(f"/pods/g{r}w{i}")
        return {
            "submit": _percentiles(lat),
            "gang_submit": _percentiles(gang_lat),
            "gang_contiguity": contig,
        }
    finally:
        controller.shutdown()
        for a in agents:
            a.shutdown()


def config12(rounds=None):
    """adversarial: 2000-node full-sweep worst case — no perfect node anywhere (saturated scalar sweep, fragmented geometry sweep, needle-at-the-end placement): p50/p99 per sweep kind"""
    import re

    rounds = rounds or 15
    n_nodes = 2000
    c = Cluster()
    for i in range(n_nodes):
        c.register_node(
            f"n{i:04d}",
            device=new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-8", slice_uid=f"frag{i}")
            ),
        )
    # Fragment EVERY node so a 4-chip pod never finds a contiguous block:
    # hold chips {0,2,3,5} of the 2x4 grid, leaving free {1,4,6,7} —
    # 4 free chips (scalar check passes) whose coords ((0,1),(1,0),(1,2),
    # (1,3)) have no contiguous 4-set. The sweep must therefore visit all
    # 2000 nodes and reject each on GEOMETRY — the documented worst case
    # (BASELINE.md "no perfect node anywhere").
    chip_re = re.compile(r"/tpu/(\d+)/cards$")
    keep_free = {1, 4, 6, 7}
    t0 = time.perf_counter()
    for i in range(n_nodes):
        name = f"n{i:04d}"
        held = []
        for s in range(8):
            p = c.schedule(_tpu_pod(f"h{i}-{s}", 1), lambda n, nn=name: n == nn)
            key = next(iter(p.running_containers["main"].allocate_from))
            chip = int(chip_re.search(key).group(1))
            held.append((chip, p.name))
        for chip, pname in held:
            if chip in keep_free:
                c.release(pname)
    setup_s = time.perf_counter() - t0

    frag_lat = []
    for r in range(rounds):
        t0 = time.perf_counter()
        try:
            c.schedule(_tpu_pod(f"f{r}", 4))
            raise RuntimeError("fragmented cluster unexpectedly fit a 4-chip pod")
        except SchedulingError:
            frag_lat.append((time.perf_counter() - t0) * 1e3)

    # saturated-style sweep: the request exceeds every node's capacity, so
    # each node rejects on the SCALAR pre-filter alone
    sat_lat = []
    for r in range(rounds):
        t0 = time.perf_counter()
        try:
            c.schedule(_tpu_pod(f"s{r}", 9))
        except SchedulingError:
            sat_lat.append((time.perf_counter() - t0) * 1e3)

    # needle at the end: ONE pristine node sorting last — the sweep scans
    # all 2000 fragmented nodes, then places on the needle (and must reach
    # it: perfect-score early exit only fires when the node is seen)
    c.register_node(
        "zz-needle",
        device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8", slice_uid="needle")),
    )
    needle_lat = []
    for r in range(rounds):
        t0 = time.perf_counter()
        p = c.schedule(_tpu_pod(f"z{r}", 4))
        needle_lat.append((time.perf_counter() - t0) * 1e3)
        assert p.node_name == "zz-needle"
        c.release(p.name)
    return {
        "nodes": n_nodes,
        "setup_s": round(setup_s, 2),
        "fragmented_sweep": _percentiles(frag_lat),
        "saturated_sweep": _percentiles(sat_lat),
        "needle_placement": _percentiles(needle_lat),
    }


def config13(rounds=None):
    """reservation vs starvation: a 16-chip gang queued under small-pod churn — passes-to-assemble with the head-of-line reservation on vs the demonstrated starvation with it off"""
    from kubetpu.wire.controller import ControllerServer, pod_to_json

    # floor of 10: the reserved branch needs ~6 passes to age + drain the
    # four holders; fewer rounds would fail the assembly assertion below
    rounds = max(rounds or 40, 10)
    out = {}
    for label, reserve_after in (("reserved", 2), ("unreserved", 0)):
        c = Cluster()
        for h in (0, 2):
            c.register_node(
                f"h{h}",
                device=new_fake_tpu_dev_manager(
                    make_fake_tpus_info("v5e-64", host_index=h)
                ),
            )
        ctl = ControllerServer(cluster=c, poll_interval=3600,
                               reserve_after=reserve_after)
        try:
            # steady state: four 4-chip pods hold all 16 chips
            for i in range(4):
                ctl._submit({"pod": pod_to_json(_tpu_pod(f"s{i}", 4))})
            ctl._submit({
                "gang": [pod_to_json(_tpu_pod("g0", 8)),
                         pod_to_json(_tpu_pod("g1", 8))],
                "queue": True,
            })
            # churn: every pass one small job finishes, a new one arrives
            placed_smalls = [f"s{i}" for i in range(4)]
            next_small = 4
            assembled_at = None
            poll_lat = []
            for r in range(rounds):
                if placed_smalls:
                    done = placed_smalls.pop(0)
                    with ctl._lock:
                        try:
                            c.release(done)
                        except KeyError:
                            pass
                sub = ctl._submit(
                    {"pod": pod_to_json(_tpu_pod(f"s{next_small}", 4)),
                     "queue": True})
                # before the reservation activates (or with it off), the
                # new small places DIRECTLY at submit — track it for
                # later release
                placed_smalls.extend(
                    p["pod"] for p in sub.get("placements", [])
                )
                next_small += 1
                t0 = time.perf_counter()
                res = ctl.poll_once()
                poll_lat.append((time.perf_counter() - t0) * 1e3)
                placed_smalls.extend(
                    e["pod"] for e in res["rescheduled"]
                    if e["pod"].startswith("s")
                )
                if assembled_at is None and any(
                    e["pod"] == "g0" for e in res["rescheduled"]
                ):
                    assembled_at = r + 1
                    break
            out[label] = {
                "gang_assembled": assembled_at is not None,
                "passes_to_assemble": assembled_at,
                "poll": _percentiles(poll_lat),
            }
        finally:
            # never start()ed, so no serve loop to shutdown() — just
            # release the listening socket __init__ bound
            ctl._httpd.server_close()
    # the whole point: reservation assembles the gang, FIFO-without-
    # reservation starves it under identical churn
    assert out["reserved"]["gang_assembled"]
    assert not out["unreserved"]["gang_assembled"]
    return out


def config14(rounds=None):
    """multislice: 4 fragmented v5e-256 slices; a 480-chip gang (60 hosts) that fits no single slice spans 2 slices via the opt-in knob — placement p50/p99 + per-slice contiguity"""
    from kubetpu.scheduler.meshstate import MultisliceKey

    rounds = rounds or 10
    c = Cluster()
    for s in range(4):
        for h in range(32):
            c.register_node(
                f"s{s}h{h:02d}",
                device=new_fake_tpu_dev_manager(
                    make_fake_tpus_info("v5e-256", host_index=h,
                                        slice_uid=f"slice{s}")
                ),
            )
    # fragment every slice (hold one whole host each): the 60-host gang
    # can never fit a 32-host slice regardless — the holds exist so the
    # per-slice contiguity search runs on a NON-pristine tree (routing
    # around a held host), keeping the latency number honest
    for s in range(4):
        c.schedule(_tpu_pod(f"hold{s}", 8),
                   lambda n, pre=f"s{s}h00": n == pre)

    lat, contig = [], []
    for r in range(rounds):
        pods = [
            _tpu_pod(f"g{r}w{i}", 8, **{MultisliceKey: 2}) for i in range(60)
        ]
        t0 = time.perf_counter()
        placed = c.schedule_gang(pods)
        lat.append((time.perf_counter() - t0) * 1e3)
        per = c.gang_slice_contiguity(placed)
        contig.append(min(per.values()))
        assert len(per) == 2, f"expected a 2-slice placement, got {len(per)}"
        for p in placed:
            c.release(p.name)
    return {
        **_percentiles(lat),
        "slices_spanned": 2,
        "min_per_slice_contiguity": min(contig),
    }


def churn_fleet(n_nodes, chips_per_node=8):
    """A fleet of *n_nodes* single-host v5e-8 slices (8 chips each) —
    the Round-21 fleet-churn substrate. Distinct slice uids: placement
    never straddles hosts, so per-op cost isolates the per-POD schedule
    path the fit index accelerates."""
    c = Cluster()
    for i in range(n_nodes):
        c.register_node(
            f"n{i:04d}",
            device=new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-8", slice_uid=f"s{i}")
            ),
        )
    return c


def sched_churn(cluster, rounds, seed=1234, preempt_every=150,
                prefill_util=0.60):
    """Sustained submit/release/preempt churn at ~70% fleet utilization;
    returns per-op schedule-latency percentiles. The op mix: whole-chip
    pods (1/2/4/8), vChip (fractional) pods (~30%), a high-priority
    preemptor every *preempt_every* ops, and random releases draining
    the fleet back under 70% — the steady-state a busy control plane
    actually sees, as opposed to the empty-fleet happy path. An UNTIMED
    prefill first loads the fleet to *prefill_util*, so arms of different
    fleet sizes are measured at the same operating point (a 16x-larger
    fleet would otherwise spend the whole run filling from empty while
    the small arm churns saturated — apples to oranges)."""
    import random

    from kubetpu.core.cluster import PriorityKey
    from kubetpu.scheduler import meshstate

    rng = random.Random(seed)
    cap_milli = sum(
        n.info.capacity.get(ResourceTPU, 0) for n in cluster.nodes.values()
    ) * meshstate.MILLI_PER_CHIP
    held = 0
    sizes = {}  # pod name -> milli held
    names = []  # same pods, O(1) random-victim pick (swap-pop)
    lat, failures, preemptions = [], 0, 0
    k = 0
    while cap_milli and held < prefill_util * cap_milli:
        k += 1
        if rng.random() < 0.3:
            need = rng.choice([125, 250, 500])
            pod = PodInfo(
                name=f"w{k}",
                requests={meshstate.FracKey: need},
                running_containers={"main": ContainerInfo()},
            )
        else:
            chips = rng.choice([1, 1, 2, 2, 4, 8])
            need = chips * meshstate.MILLI_PER_CHIP
            pod = _tpu_pod(f"w{k}", chips)
        try:
            placed = cluster.schedule(pod)
        except SchedulingError:
            break  # fragmented short of the target: measure from here
        sizes[placed.name] = need
        names.append(placed.name)
        held += need
    for i in range(rounds):
        if preempt_every and i and i % preempt_every == 0:
            pod = _tpu_pod(f"hi{i}", 8)
            pod.requests[PriorityKey] = 10
            t0 = time.perf_counter()
            try:
                placed, evicted = cluster.schedule_preempting(pod)
            except SchedulingError:
                failures += 1
            else:
                preemptions += 1
                sizes[placed.name] = 8 * meshstate.MILLI_PER_CHIP
                names.append(placed.name)
                held += sizes[placed.name]
                for v in evicted:
                    freed = sizes.pop(v.name, 0)
                    held -= freed
                    if freed:
                        names.remove(v.name)
            lat.append((time.perf_counter() - t0) * 1e3)
        else:
            if rng.random() < 0.3:
                need = rng.choice([125, 250, 500])
                pod = PodInfo(
                    name=f"v{i}",
                    requests={meshstate.FracKey: need},
                    running_containers={"main": ContainerInfo()},
                )
            else:
                chips = rng.choice([1, 1, 2, 2, 4, 8])
                need = chips * meshstate.MILLI_PER_CHIP
                pod = _tpu_pod(f"c{i}", chips)
            t0 = time.perf_counter()
            try:
                placed = cluster.schedule(pod)
            except SchedulingError:
                failures += 1
            else:
                sizes[placed.name] = need
                names.append(placed.name)
                held += need
            lat.append((time.perf_counter() - t0) * 1e3)
        while held > 0.70 * cap_milli and names:
            j = rng.randrange(len(names))
            names[j], names[-1] = names[-1], names[j]
            victim = names.pop()
            held -= sizes.pop(victim)
            cluster.release(victim)
    return {
        **_percentiles(lat),
        "failures": failures,
        "preemptions": preemptions,
        "final_util": round(held / cap_milli, 2) if cap_milli else 0.0,
    }


def config15(rounds=None):
    """Round-21 fleet-scale churn: per-op schedule p50/p99 on a 4096-chip fleet (512 v5e-8 hosts) vs the identical churn at 256 chips — the incremental fit index must keep the ratio sub-linear (< 3x for a 16x fleet)"""
    rounds = rounds or 600
    out = {}
    for label, n_nodes in (("chips256", 32), ("chips4096", 512)):
        t0 = time.perf_counter()
        c = churn_fleet(n_nodes)
        setup_s = time.perf_counter() - t0
        out[label] = {
            **sched_churn(c, rounds),
            "nodes": n_nodes,
            "setup_s": round(setup_s, 2),
        }
        problems = c.check_invariants()
        assert not problems, problems[:3]
        # the fleet graph is cyclic (cluster <-> index <-> hook state);
        # collect eagerly so a caller embedding this comparison in a
        # longer run (bench_gate --record) isn't left churning gen-2 GC
        # over two dead 512-node fleets during its OWN measurements
        del c
        gc.collect()
    ratio = (
        out["chips4096"]["p99_ms"] / out["chips256"]["p99_ms"]
        if out["chips256"]["p99_ms"] else float("inf")
    )
    out["p99_ratio_4096_vs_256"] = round(ratio, 2)
    out["sched_p99_ms"] = out["chips4096"]["p99_ms"]
    # the acceptance bar: 16x the fleet must cost < 3x the tail latency
    assert ratio < 3.0, (
        f"4096-chip p99 is {ratio:.2f}x the 256-chip p99 (want < 3x)"
    )
    return out


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5,
           6: config6, 7: config7, 8: config8, 9: config9, 10: config10,
           11: config11, 12: config12, 13: config13, 14: config14,
           15: config15}
TAKES_ROUNDS = {4, 8, 9, 10, 11, 12, 13, 14, 15}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="schedsim", description=__doc__)
    ap.add_argument("--config", type=int, nargs="*", choices=sorted(CONFIGS),
                    default=None,
                    help="configs to run (default: 1-7; the adversarial "
                    "configs 8-10 run only when named — see make bench-adversarial)")
    ap.add_argument("--rounds", type=int, default=None,
                    help="override per-config default round counts")
    args = ap.parse_args(argv)
    selected = args.config if args.config else [n for n in sorted(CONFIGS) if n <= 7]
    ok = True
    for n in selected:
        fn = CONFIGS[n]
        try:
            result = fn(args.rounds) if n in TAKES_ROUNDS else fn()
            print(json.dumps({"config": n, "desc": fn.__doc__, **result}))
        except Exception as e:  # noqa: BLE001
            ok = False
            print(json.dumps({"config": n, "error": str(e)}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
