"""``kubetpu-controller`` — the long-running control-plane daemon.

Holds the Cluster, registers agents, reconciles on an interval
(dead agent -> evict -> reschedule), and serves the operator HTTP API
(see ``kubetpu.wire.controller``).

    python -m kubetpu.cli.controller --agents URL [URL ...]
                                     [--port P] [--poll-interval S]

Auth: ``KUBETPU_WIRE_TOKEN`` protects the controller API and is also
used toward the agents.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from kubetpu.wire.controller import ControllerServer


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubetpu-controller", description=__doc__)
    ap.add_argument("--agents", nargs="*", default=[],
                    help="agent URLs to register at startup")
    ap.add_argument("--bind", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="API port (0 = ephemeral, printed on startup)")
    ap.add_argument("--poll-interval", type=float, default=5.0)
    ap.add_argument("--suspect-after", type=int, default=1,
                    help="consecutive missed probes before a node is "
                         "health-cordoned (pods kept, no new placements)")
    ap.add_argument("--dead-after", type=int, default=3,
                    help="consecutive missed probes before a node is "
                         "evicted and its pods rescheduled (1 = legacy "
                         "one-strike)")
    ap.add_argument("--probation-passes", type=int, default=1,
                    help="clean probes a recovering node must answer "
                         "while on probation before taking new work")
    ap.add_argument("--trace-sink", default=None, metavar="PATH",
                    help="append every finished trace span to PATH as JSON "
                         "lines (also via KUBETPU_TRACE_SINK)")
    args = ap.parse_args(argv)

    if args.trace_sink:
        from kubetpu.obs import trace as obs_trace

        obs_trace.tracer().set_sink(args.trace_sink)

    token = os.environ.get("KUBETPU_WIRE_TOKEN")
    server = ControllerServer(
        host=args.bind, port=args.port, poll_interval=args.poll_interval,
        token=token, suspect_after=args.suspect_after,
        dead_after=args.dead_after, probation_passes=args.probation_passes,
    )
    registered, skipped = [], []
    for url in args.agents:
        try:
            registered.append(server.register_agent(url, token=token))
        except Exception as e:  # noqa: BLE001 — one dead agent must not
            # crash-loop the whole control plane (the outage the reconcile
            # loop exists to survive); re-register later via POST /nodes
            print(f"warning: agent {url} not registered ({e}); "
                  f"retry with POST /nodes", file=sys.stderr)
            skipped.append(url)
    addr = server.start()
    print(json.dumps({"listening": addr, "nodes": registered,
                      "skipped": skipped}), flush=True)
    try:
        server.wait()
    except KeyboardInterrupt:
        server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
