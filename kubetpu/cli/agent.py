"""``kubetpu-agent`` — the long-running node agent.

The process-topology counterpart of the reference's CRI-shim side (process
A in SURVEY.md §3): loads the device plugin, probes on a cadence (the
manager's 5-minute probe cache bounds actual hardware queries), and serves
the node to the control plane.

Two modes:

- ``--serve`` (the real wire): an HTTP server exposing ``GET /nodeinfo`` +
  ``POST /allocate`` (see ``kubetpu.wire.server``). On startup it prints ONE
  JSON line ``{"listening": "http://...", "node": ...}`` so spawners can
  discover the ephemeral port, then serves until killed. The control plane
  registers it via ``Cluster.register_remote_node(url)``.
- legacy stream mode (default): emit the advertisement as a JSON line
  whenever it changes — for operator pipes and diagnostics.

    python -m kubetpu.cli.agent --serve [--port P] [--name NODE]
                                [--fake TOPO] [--host N] [--slice-uid UID]
    python -m kubetpu.cli.agent [--fake TOPO] [--host N] [--interval S]
                                [--iterations N]
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

from kubetpu.api.types import new_node_info


def _advertisement(dev) -> dict:
    info = new_node_info("local")
    dev.update_node_info(info)
    return {
        "capacity": info.capacity,
        "allocatable": info.allocatable,
        "kube_cap": info.kube_cap,
        "kube_alloc": info.kube_alloc,
    }


def _make_device(args):
    if args.device_class == "gpu":
        # GPU agents always probe through the native gpuinfo binary (the
        # reference's nvmlinfo exec boundary); --fake pins a fixture box
        from kubetpu.device.nvidia import new_native_nvidia_gpu_manager

        extra = ["--fake", args.fake] if args.fake else None
        dev = new_native_nvidia_gpu_manager(extra_args=extra)
    elif args.fake and args.native:
        # REAL exec boundary, fixture topology: tpuinfo --fake ... — the
        # heterogeneous wire story (BASELINE config 5) runs exactly this
        from kubetpu.device import new_tpu_dev_manager

        extra = ["--fake", args.fake, "--host", str(args.host),
                 "--slice", args.slice_uid]
        if args.missing:
            extra += ["--missing", args.missing]
        dev = new_tpu_dev_manager(extra_args=extra)
    elif args.fake:
        from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager

        missing = tuple(int(x) for x in args.missing.split(",") if x) if args.missing else ()
        dev = new_fake_tpu_dev_manager(
            make_fake_tpus_info(
                args.fake, args.host, missing_chips=missing, slice_uid=args.slice_uid
            )
        )
    else:
        from kubetpu.device import new_tpu_dev_manager

        dev = new_tpu_dev_manager()
    dev.start()
    return dev


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubetpu-agent", description=__doc__)
    ap.add_argument("--fake", metavar="TOPO", default=None,
                    help="fake backend topology (e.g. v5e-8, or titan8/k80x4 "
                         "with --device-class gpu); default: native probe")
    ap.add_argument("--device-class", choices=["tpu", "gpu"], default="tpu",
                    help="which device family this node serves")
    ap.add_argument("--native", action="store_true",
                    help="probe through the native binary even in --fake "
                         "mode (tpuinfo --fake TOPO behind the exec-JSON "
                         "boundary)")
    ap.add_argument("--host", type=int, default=0)
    ap.add_argument("--slice-uid", default="slice0",
                    help="physical slice uid for the fake backend")
    ap.add_argument("--missing", default="",
                    help="comma-separated local chip ids to fault-inject as absent")
    ap.add_argument("--serve", action="store_true",
                    help="serve the agent HTTP wire instead of streaming JSON lines")
    ap.add_argument("--bind", default="127.0.0.1", help="--serve bind address")
    ap.add_argument("--port", type=int, default=0,
                    help="--serve port (0 = ephemeral, printed on startup)")
    ap.add_argument("--name", default=None,
                    help="node name to advertise (default: <topo>-h<host> or 'local')")
    ap.add_argument("--interval", type=float, default=60.0,
                    help="stream mode: seconds between advertisement refreshes")
    ap.add_argument("--iterations", type=int, default=0,
                    help="stream mode: stop after N refreshes (0 = run forever)")
    ap.add_argument("--trace-sink", default=None, metavar="PATH",
                    help="append every finished trace span to PATH as JSON "
                         "lines (also via KUBETPU_TRACE_SINK)")
    args = ap.parse_args(argv)

    if args.trace_sink:
        from kubetpu.obs import trace as obs_trace

        obs_trace.tracer().set_sink(args.trace_sink)

    if args.device_class == "gpu":
        # TPU-topology flags silently dropped on the floor would make a
        # resilience test quietly test the wrong topology — reject them
        bad = [
            flag for flag, val, default in (
                ("--missing", args.missing, ""),
                ("--native", args.native, False),
                ("--host", args.host, 0),
                ("--slice-uid", args.slice_uid, "slice0"),
            ) if val != default
        ]
        if bad:
            ap.error(f"{', '.join(bad)} not supported with --device-class gpu")

    dev = _make_device(args)

    if args.serve:
        import os
        import signal

        from kubetpu.wire import NodeAgentServer

        name = args.name or (f"{args.fake}-h{args.host}" if args.fake else "local")
        server = NodeAgentServer(
            dev, name, host=args.bind, port=args.port,
            token=os.environ.get("KUBETPU_WIRE_TOKEN"),
        )
        # SIGTERM = graceful stop: drain (new work 503s), finish in-flight
        # requests, then exit — the operator's rolling-restart contract
        signal.signal(
            signal.SIGTERM,
            lambda *_: threading.Thread(
                target=server.shutdown, daemon=True
            ).start(),
        )
        print(json.dumps({"listening": server.address, "node": name}), flush=True)
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            server.shutdown()
        return 0

    last = None
    iteration = 0
    while True:
        iteration += 1
        try:
            adv = _advertisement(dev)
        except Exception as e:  # noqa: BLE001 — degrade, keep running
            adv = {"error": str(e)}
        if adv != last:
            print(json.dumps({"ts": time.time(), **adv}), flush=True)
            last = adv
        if args.iterations and iteration >= args.iterations:
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
