"""``kubetpu-agent`` — the long-running node agent.

The process-topology counterpart of the reference's CRI-shim side (process
A in SURVEY.md §3): loads the device plugin, probes on a cadence (the
manager's 5-minute probe cache bounds actual hardware queries), and emits
the node's advertisement as a JSON line whenever it changes — the stream a
control plane (or an operator's pipe) consumes.

    python -m kubetpu.cli.agent [--fake TOPO] [--host N] [--interval S]
                                [--iterations N]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from kubetpu.api.types import new_node_info


def _advertisement(dev) -> dict:
    info = new_node_info("local")
    dev.update_node_info(info)
    return {
        "capacity": info.capacity,
        "allocatable": info.allocatable,
        "kube_cap": info.kube_cap,
        "kube_alloc": info.kube_alloc,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubetpu-agent", description=__doc__)
    ap.add_argument("--fake", metavar="TOPO", default=None,
                    help="fake backend topology (e.g. v5e-8); default: native probe")
    ap.add_argument("--host", type=int, default=0)
    ap.add_argument("--interval", type=float, default=60.0,
                    help="seconds between advertisement refreshes")
    ap.add_argument("--iterations", type=int, default=0,
                    help="stop after N refreshes (0 = run forever)")
    args = ap.parse_args(argv)

    if args.fake:
        from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager

        dev = new_fake_tpu_dev_manager(make_fake_tpus_info(args.fake, args.host))
    else:
        from kubetpu.device import new_tpu_dev_manager

        dev = new_tpu_dev_manager()
    dev.start()

    last = None
    iteration = 0
    while True:
        iteration += 1
        try:
            adv = _advertisement(dev)
        except Exception as e:  # noqa: BLE001 — degrade, keep running
            adv = {"error": str(e)}
        if adv != last:
            print(json.dumps({"ts": time.time(), **adv}), flush=True)
            last = adv
        if args.iterations and iteration >= args.iterations:
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
