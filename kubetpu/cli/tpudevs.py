"""``tpudevs`` — device-plugin lifecycle smoke CLI.

Analog of the reference's ``nvidiadevs`` (``nvidiagpuplugin/cmd/main.go``):
``--plugin=false`` probes hardware directly through the exec-JSON client;
``--plugin=true`` loads the device plugin module by its factory contract and
drives the full New -> Start -> UpdateNodeInfo lifecycle, printing the
resulting NodeInfo — doubling as the plugin-loading smoke test.

    python -m kubetpu.cli.tpudevs [--plugin] [--plugin-path P] [--fake TOPO]
"""

from __future__ import annotations

import argparse
import json
import sys

from kubetpu.api.device import create_device_from_plugin
from kubetpu.api.types import new_node_info


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpudevs", description=__doc__)
    ap.add_argument("--plugin", action="store_true",
                    help="load the device plugin and drive the full lifecycle")
    ap.add_argument("--plugin-path", default="kubetpu.device.plugin",
                    help="device plugin module (dotted path or .py file)")
    ap.add_argument("--fake", metavar="TOPO", default=None,
                    help="use a fake backend with this topology (e.g. v5e-8)")
    ap.add_argument("--host", type=int, default=0, help="fake host index")
    args = ap.parse_args(argv)

    if not args.plugin:
        print("Not using plugin")
        if args.fake:
            from kubetpu.device import make_fake_tpus_info
            from kubetpu.device.types import dump_tpus_info

            print(dump_tpus_info(make_fake_tpus_info(args.fake, args.host)))
            return 0
        from kubetpu.device import types as tputypes

        try:
            info = tputypes.get_devices()
        except Exception as e:  # noqa: BLE001
            print(f"Err: {e} Devices: none")
            return 1
        print(f"Err: None Devices: {tputypes.dump_tpus_info(info)}")
        return 0

    print("Using plugin")
    if args.fake:
        from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager

        dev = new_fake_tpu_dev_manager(make_fake_tpus_info(args.fake, args.host))
    else:
        dev = create_device_from_plugin(args.plugin_path)
        dev.new()
    dev.start()
    node_info = new_node_info("local")
    try:
        dev.update_node_info(node_info)
    except Exception as e:  # noqa: BLE001
        print(f"UpdateNodeInfo encounters error {e}")
        return 1
    print("NodeInfo:")
    print(json.dumps({
        "name": node_info.name,
        "capacity": node_info.capacity,
        "allocatable": node_info.allocatable,
        "kube_cap": node_info.kube_cap,
        "kube_alloc": node_info.kube_alloc,
    }, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
