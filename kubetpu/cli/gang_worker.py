"""``kubetpu-gang-worker`` — one gang member as a REAL OS process.

The inside-the-container entry point a launcher starts once per gang pod:
builds the worker's ``LaunchConfig`` from the injected allocation env
(``TPU_VISIBLE_DEVICES``/``TPU_WORKER_ID`` — the env the device manager's
Allocate emitted, SURVEY.md §3.4) plus the gang facts only the launcher
knows (coordinator address, gang size, this worker's rank), joins the
``jax.distributed`` process group, and runs one data-parallel train step
whose gradient all-reduce crosses the process boundary
(``kubetpu.jobs.launch.run_gang_worker``).

Prints ONE JSON line::

    {"process_index": 0, "process_count": 2, "global_devices": 2,
     "loss": 5.01}

identical ``loss`` on every member certifies the cross-process psum.

    python -m kubetpu.cli.gang_worker --coordinator HOST:PORT \
        --num-processes N --rank R [--platform cpu]

``--platform cpu`` is the hardware-free CI path (gloo collectives over
TCP); on a real multi-host TPU slice omit it and the libtpu backend rides
ICI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--coordinator", required=True, help="rank-0 host:port")
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--rank", type=int, required=True,
                    help="gang rank (position in the placed gang, NOT "
                         "necessarily the host's TPU_WORKER_ID)")
    ap.add_argument("--platform", default=None,
                    help="pin a jax platform ('cpu' for hardware-free runs)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    from kubetpu.jobs.launch import LaunchConfig, run_gang_worker

    visible = os.environ.get("TPU_VISIBLE_DEVICES", "")
    local_ids = [int(x) for x in visible.split(",") if x != ""] or [0]
    config = LaunchConfig(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.rank,
        local_device_ids=local_ids,
    )
    out = run_gang_worker(config, platform=args.platform, seed=args.seed)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
