"""Incremental fit index: flat-latency candidate generation at fleet scale.

``Cluster._schedule_inner`` is a resumable predicate sweep — correct, but
O(fleet) per pod, and at 4096+ chips the control plane becomes the
bottleneck before the data plane does. This module keeps per-node summaries
of exactly the quantities the schedulers' *cheapest* predicate pre-filters
read, bucketed for range queries:

- ``free_tpu``: the advertised TPU scalar (``allocatable[ResourceTPU]``) —
  the quantity ``TpuScheduler.pod_fits_device`` compares against ``want``
  before doing any geometry. NOTE: this counts whole-held chips only; a
  fractionally-occupied chip still reads free here, exactly as the
  predicate sees it.
- ``whole_free``: the count of WHOLE-free chips (``NodeMeshState.free``) —
  for mesh nodes the geometry search can only place ``n`` whole chips if
  ``n`` whole-free chips exist, so the whole-chip bucket key
  (``tpu_key``) is ``whole_free`` there (a strictly tighter sound prune
  than the scalar on nodes carrying vChip occupants) and ``free_tpu`` on
  non-mesh nodes, where the scalar is the predicate's only check.
- ``free_gpu``: the advertised GPU scalar, mirroring the GpuScheduler
  pre-filter.
- ``fracs``: a remainder -> chip-count multiset over
  ``NodeMeshState.frac_free`` — ``_frac_fit`` rejects a node iff no chip
  has ``frac_free >= frac``, so a node is vChip-eligible iff it has a
  bucket at or above the request.
- ``free_milli``: the node's total fractional capacity
  (``NodeMeshState.free_milli()``), consumed by the gang milli pre-filter.

Soundness contract (the equivalence argument, ARCHITECTURE.md §Round-21):
the index is used ONLY to discard nodes that *provably fail* one of those
pre-filters. The surviving candidates flow through the unchanged sweep
machinery — same sorted order, same ``pod_fits_device`` calls, same
early-exit bound, same fill-failure demotion — so the placement decision
(node, score, tie-break) is identical to the full sweep by construction.
A node the index drops would have been rejected by the predicate's first
comparison; a node the index keeps is re-checked from scratch. The index
can therefore be stale-conservative but never stale-optimistic, which is
why invalidation granularity is "mark the node dirty, recompute lazily at
the next query" rather than incremental deltas.

Invalidation rides the existing choke points: every in-place mutator of an
advertised ResourceList already calls ``meshstate.invalidate_mesh_state``
(the parse-memo contract), and the cluster registers a dirty hook there per
live dict. Lifecycle paths that *replace* the dict (register/refresh/
remove) re-register explicitly. No accounting code gained new call sites.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from kubetpu.api.types import ResourceList
from kubetpu.scheduler import meshstate
from kubetpu.scheduler.deviceclass import GPU, TPU


@dataclass
class NodeFitEntry:
    """One node's summary of the cheap predicate pre-filters."""

    free_tpu: int
    free_gpu: int
    # free-milli remainder -> number of chips at that remainder (>=1 only:
    # a 0-remainder chip cannot host any vChip). Pristine vChip-capable
    # chips appear at MILLI_PER_CHIP.
    fracs: Dict[int, int] = field(default_factory=dict)
    free_milli: int = 0
    # chips that are WHOLE-free (no whole hold, no fractional occupant) —
    # the size of NodeMeshState.free. A contiguous n-chip placement needs
    # n whole-free chips, so for mesh nodes this is a tighter sound prune
    # key than the scalar (which still counts fractionally-occupied chips).
    whole_free: int = 0
    has_mesh: bool = False

    @property
    def tpu_key(self) -> int:
        """The whole-chip bucket key: an upper bound on how many whole
        chips a placement could possibly take from this node. For mesh
        nodes the geometry search draws only from whole-free chips; for
        non-mesh nodes the scalar is the predicate's only check."""
        return self.whole_free if self.has_mesh else self.free_tpu


def _compute_entry(alloc: ResourceList) -> NodeFitEntry:
    """Recompute a node's summary from its advertised list — the single
    definition of what the index believes, shared by refresh and audit."""
    state = meshstate.parse_mesh_state(alloc)
    fracs: Dict[int, int] = {}
    free_milli = 0
    whole_free = 0
    if state is not None:
        for rem in state.frac_free.values():
            if rem >= 1:
                fracs[rem] = fracs.get(rem, 0) + 1
        free_milli = state.free_milli()
        whole_free = len(state.free)
    return NodeFitEntry(
        free_tpu=int(alloc.get(TPU.resource_name, 0)),
        free_gpu=int(alloc.get(GPU.resource_name, 0)),
        fracs=fracs,
        free_milli=free_milli,
        whole_free=whole_free,
        has_mesh=state is not None,
    )


class FitIndex:
    """Bucket indexes over NodeFitEntry, with lazy dirty refresh.

    Buckets map an exact value (free count / frac remainder) to the set of
    node names at that value; an "at least n" query unions the buckets with
    key >= n. Key cardinality is tiny in practice (free counts bounded by
    chips-per-host, remainders by the distinct vChip sizes in flight), so
    the union is far cheaper than touching every node.
    """

    def __init__(self) -> None:
        self.entries: Dict[str, NodeFitEntry] = {}
        self.dirty: Set[str] = set()
        self.tpu_buckets: Dict[int, Set[str]] = {}
        self.gpu_buckets: Dict[int, Set[str]] = {}
        self.frac_buckets: Dict[int, Set[str]] = {}
        self.stats = {"refreshes": 0, "queries": 0}

    # -- membership maintenance ------------------------------------------

    def _bucket_add(self, name: str, entry: NodeFitEntry) -> None:
        self.tpu_buckets.setdefault(entry.tpu_key, set()).add(name)
        self.gpu_buckets.setdefault(entry.free_gpu, set()).add(name)
        for rem in entry.fracs:
            self.frac_buckets.setdefault(rem, set()).add(name)

    def _bucket_remove(self, name: str, entry: NodeFitEntry) -> None:
        for buckets, key in ((self.tpu_buckets, entry.tpu_key),
                             (self.gpu_buckets, entry.free_gpu)):
            members = buckets.get(key)
            if members is not None:
                members.discard(name)
                if not members:
                    del buckets[key]
        for rem in entry.fracs:
            members = self.frac_buckets.get(rem)
            if members is not None:
                members.discard(name)
                if not members:
                    del self.frac_buckets[rem]

    def register(self, name: str, alloc: ResourceList) -> None:
        """(Re)compute and insert a node's entry eagerly — lifecycle path
        (node registered / allocatable dict replaced)."""
        old = self.entries.pop(name, None)
        if old is not None:
            self._bucket_remove(name, old)
        entry = _compute_entry(alloc)
        self.entries[name] = entry
        self._bucket_add(name, entry)
        self.dirty.discard(name)
        self.stats["refreshes"] += 1

    def unregister(self, name: str) -> None:
        old = self.entries.pop(name, None)
        if old is not None:
            self._bucket_remove(name, old)
        self.dirty.discard(name)

    def mark_dirty(self, name: str) -> None:
        """Accounting mutated this node's advertised list — recompute at
        the next query (O(1) now, one parse later)."""
        if name in self.entries:
            self.dirty.add(name)

    def ensure_fresh(
        self, resolver: Callable[[str], Optional[ResourceList]]
    ) -> None:
        """Refresh every dirty entry from ground truth. ``resolver`` maps a
        name to its CURRENT allocatable dict (the dict object may have been
        replaced since the entry was built); None drops the entry."""
        if not self.dirty:
            return
        for name in list(self.dirty):
            alloc = resolver(name)
            if alloc is None:
                self.unregister(name)
            else:
                self.register(name, alloc)
        self.dirty.clear()

    # -- queries ----------------------------------------------------------

    @staticmethod
    def _at_least(buckets: Dict[int, Set[str]], minimum: int) -> Set[str]:
        out: Set[str] = set()
        for key, members in buckets.items():
            if key >= minimum:
                out |= members
        return out

    def eligible(
        self, want_tpu: int, want_gpu: int, frac: int
    ) -> Optional[Set[str]]:
        """Names that can possibly pass the schedulers' cheap pre-filters
        for these needs; None when the pod is unconstrained (nothing to
        prune on — caller must sweep). Callers must ensure_fresh first."""
        self.stats["queries"] += 1
        result: Optional[Set[str]] = None
        if frac > 0:
            result = self._at_least(self.frac_buckets, frac)
        if want_tpu > 0:
            names = self._at_least(self.tpu_buckets, want_tpu)
            result = names if result is None else (result & names)
        if want_gpu > 0:
            names = self._at_least(self.gpu_buckets, want_gpu)
            result = names if result is None else (result & names)
        return result

    def frac_ordered(self, frac: int) -> List[Tuple[str, float]]:
        """vChip candidates as ``(name, score)`` in the EXACT order the
        best-first sweep should visit them: descending score, name-ascending
        within a score. For a pure-frac pod the TpuScheduler score is a
        strictly decreasing function of the node's minimal fitting remainder
        — which is precisely the smallest ``frac_buckets`` key >= *frac*
        that holds the node — so the index can hand the sweep not just the
        candidate set but each candidate's exact score as a visit cap.
        ``_schedule_inner`` then settles as soon as its best evaluated node
        meets the cap of the next unvisited one: O(1) predicate
        evaluations per placement attempt instead of O(eligible nodes).
        Soundness requires the caps to be EXACT (score == cap for every
        fitting node) — Cluster gates this path on the stock scheduler set
        (Tpu+Gpu only, where every non-frac contribution is 0.0)."""
        self.stats["queries"] += 1
        keys = sorted(r for r in self.frac_buckets if r >= frac)
        seen: Set[str] = set()
        out: List[Tuple[str, float]] = []
        milli = meshstate.MILLI_PER_CHIP
        for rem in keys:
            # ascending remainder == descending score; a node's FIRST
            # appearance is at its minimal fitting remainder = its score
            score = (milli - (rem - frac)) / float(milli)
            for name in sorted(self.frac_buckets[rem] - seen):
                seen.add(name)
                out.append((name, score))
        return out

    # -- consistency ------------------------------------------------------

    def audit(self, allocs: Dict[str, ResourceList]) -> List[str]:
        """Compare the index against ground truth; returns human-readable
        problems (empty = consistent). Dirty entries are exempt from the
        value comparison — lazy staleness is the design, they refresh at
        the next query — but registry membership and bucket structure must
        always agree. Feeds Cluster.check_invariants."""
        problems: List[str] = []
        for name in allocs:
            if name not in self.entries:
                problems.append(f"fit index: registered node {name!r} has no entry")
        for name in self.entries:
            if name not in allocs:
                problems.append(f"fit index: phantom entry {name!r} (node not registered)")
        for name, entry in sorted(self.entries.items()):
            alloc = allocs.get(name)
            if alloc is not None and name not in self.dirty:
                expected = _compute_entry(alloc)
                if entry != expected:
                    problems.append(
                        f"fit index: clean entry for {name!r} drifted from "
                        f"accounting: {entry} != {expected}"
                    )
            # bucket membership must mirror the entry regardless of dirt
            if name not in self.tpu_buckets.get(entry.tpu_key, ()):
                problems.append(
                    f"fit index: {name!r} missing from tpu bucket {entry.tpu_key}"
                )
            if name not in self.gpu_buckets.get(entry.free_gpu, ()):
                problems.append(
                    f"fit index: {name!r} missing from gpu bucket {entry.free_gpu}"
                )
            for rem in entry.fracs:
                if name not in self.frac_buckets.get(rem, ()):
                    problems.append(
                        f"fit index: {name!r} missing from frac bucket {rem}"
                    )
        for label, buckets in (("tpu", self.tpu_buckets),
                               ("gpu", self.gpu_buckets),
                               ("frac", self.frac_buckets)):
            for key, members in buckets.items():
                for name in members:
                    entry = self.entries.get(name)
                    if entry is None:
                        problems.append(
                            f"fit index: {label} bucket {key} holds "
                            f"unregistered node {name!r}"
                        )
                        continue
                    owned = (
                        entry.fracs if label == "frac"
                        else {entry.tpu_key} if label == "tpu"
                        else {entry.free_gpu}
                    )
                    if key not in owned:
                        problems.append(
                            f"fit index: {label} bucket {key} holds {name!r} "
                            f"whose entry says {sorted(owned)}"
                        )
        return problems
