"""The NVIDIA GPU scheduler plugin — tree-ranked, for heterogeneous
clusters (BASELINE config 5).

A faithful functional mirror of the reference ``NvidiaGPUScheduler``
(``gpuschedulerplugin/gpu_scheduler.go``): request translation to the node's
2-level NVLink grouping, topology-shape caching, auto-topology via the best
cached tree. Kept tree-scored (depth/density), since NVLink locality has no
torus geometry.
"""

from __future__ import annotations

from kubetpu.api import utils
from kubetpu.api.devicescheduler import DeviceScheduler, FitResult, PredicateFailureReason
from kubetpu.api.types import DeviceGroupPrefix, NodeInfo, PodInfo
from kubetpu.scheduler import meshstate
from kubetpu.scheduler.deviceclass import GPU
from kubetpu.scheduler.translate import (
    pod_wants_device,
    prepare_pod,
    translate_device_resources,
    translate_pod_device_resources,
)
from kubetpu.scheduler.treecache import NodeTreeCache, compute_tree_score

# reference GPUTopologyGeneration (gpu_scheduler.go:12-15)
GPUTopologyGeneration = GPU.topology_gen_key


class GpuScheduler(DeviceScheduler):
    def __init__(self) -> None:
        self._cache = NodeTreeCache(GPU.grp_prefix, "cards", levels=1)

    def add_node(self, node_name: str, node_info: NodeInfo) -> None:
        """Force translation to two levels via a synthetic grouped 1-GPU
        node list (reference AddNode, gpu_scheduler.go:21-28)."""
        synthetic = {
            DeviceGroupPrefix + "/gpugrp1/A/gpugrp0/B/gpu/GPU0/cards": 1,
        }
        # In-place mutation of allocatable follows — invalidate the mesh
        # memo keyed on this dict (same contract as TpuScheduler.add_node).
        meshstate.invalidate_mesh_state(node_info.allocatable)
        node_info.allocatable = translate_device_resources(
            GPU,
            node_info.kube_alloc.get(GPU.resource_name, 0),
            synthetic,
            node_info.allocatable,
        )
        utils.logf(4, "AllocAddNode: %s", node_info.allocatable)
        self._cache.add_resources(node_name, node_info.allocatable)

    def remove_node(self, node_name: str) -> None:
        self._cache.remove_node(node_name)

    def pod_fits_device(
        self, node_info: NodeInfo, pod_info: PodInfo, fill_allocate_from: bool
    ) -> FitResult:
        # Pod-memoized shaping + scalar pre-filter before translation (same
        # rationale as TpuScheduler.pod_fits_device: per-node work only for
        # nodes that can actually host the pod).
        want, has_base = prepare_pod(GPU, pod_info)
        if want == 0 and not has_base:
            # TPU-only pod: GPU translation would be a no-op — skip the
            # per-node key scan entirely (see TpuScheduler.pod_fits_device).
            return True, [], 0.0
        if want > 0 and node_info.allocatable.get(GPU.resource_name, 0) < want:
            reason = PredicateFailureReason(
                resource_name=GPU.resource_name,
                requested=int(want),
                capacity=int(node_info.allocatable.get(GPU.resource_name, 0)),
                message="insufficient free GPUs",
            )
            return False, [reason], 0.0
        err, found = translate_pod_device_resources(GPU, self._cache, node_info, pod_info)
        if err is not None or not found:
            return False, [], 0.0
        if want == 0:
            # No GPUs requested: fit trivially, contribute nothing to the
            # cross-scheduler score sum (a TPU pod's ranking must not be
            # steered by NVLink tree density).
            return True, [], 0.0
        # (scalar sufficiency was already established by the pre-filter)
        # Rank by this node's tree score so denser NVLink grouping wins ties
        # (the reference returns 0.0 and lets the core's group scheduler
        # decide, gpu_scheduler.go:34-44; kubetpu surfaces the score).
        tree = self._cache.node_tree(node_info.name)
        score = compute_tree_score(tree) if tree is not None else 0.0
        return True, [], score

    def pod_allocate(self, node_info: NodeInfo, pod_info: PodInfo) -> None:
        err, found = translate_pod_device_resources(GPU, self._cache, node_info, pod_info)
        if err is not None:
            raise RuntimeError(err)
        if not found:
            raise RuntimeError("translate_pod_device_resources found no translation")

    def take_pod_resources(self, node_info: NodeInfo, pod_info: PodInfo) -> None:
        """No-op (reference gpu_scheduler.go:57-59)."""

    def return_pod_resources(self, node_info: NodeInfo, pod_info: PodInfo) -> None:
        """No-op (reference gpu_scheduler.go:61-63)."""

    def perfect_score(self, pod_info: PodInfo):
        """Tree scores (NVLink density) have no universal maximum, so GPU
        pods get no early-exit bound; pods requesting no GPUs always score
        0.0 here (see pod_fits_device)."""
        return None if pod_wants_device(GPU, pod_info) else 0.0

    def get_name(self) -> str:
        return "nvidiagpu"

    def using_group_scheduler(self) -> bool:
        return True

    def cache_shapes(self):
        return self._cache.shapes()
