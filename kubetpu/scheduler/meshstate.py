"""Per-node ICI mesh state, reconstructed from advertised resources.

The TPU device manager advertises one geometry key per node,

    resource/group/tpu-slice/<topology-name>/<host-index>: 1

alongside the per-chip grouped card keys. Chip local id <-> torus coordinate
is a fixed bijection (row-major within the host's block), so the scheduler
can reconstruct full geometry from the ResourceList alone — the source of
truth is always the advertised resources (the reference's stateless
rebuild-from-probe contract, SURVEY.md §5.4). ``parse_mesh_state`` keeps a
derived-data memo purely as a hot-path optimization; its invalidation
contract is documented at the memo below, and the single in-place mutator of
advertised lists (core accounting) invalidates explicitly. Multi-host slices
share <topology-name>; each host advertises its own <host-index>, giving
gang placement a global coordinate frame.
"""

from __future__ import annotations

import re
import weakref
from dataclasses import dataclass
from typing import Dict, Optional, Set

from kubetpu.api.types import DeviceGroupPrefix, ResourceList
from kubetpu.plugintypes.mesh import TOPOLOGIES, Coord, TpuTopology
from kubetpu.plugintypes.treetypes import ResourceTPU

# resource/group/tpu-slice/<topology-name>/<slice-uid>/<host-index>
# (legacy 3-segment form without the slice uid is accepted: a cluster with a
# single anonymous slice per topology)
SLICE_KEY_RE = re.compile(
    re.escape(DeviceGroupPrefix) + r"/tpu-slice/([^/]+)(?:/([^/]+))?/(\d+)$"
)
# any grouped per-chip cards key: .../tpu/<localid>/cards
CHIP_CARDS_RE = re.compile(r".*/tpu/(\d+)/cards$")
# any grouped per-chip fractional-capacity key: .../tpu/<localid>/milli
# (Round-18 vChips: the chip's capacity in milli-chips, 1000 = whole)
CHIP_MILLI_RE = re.compile(r".*/tpu/(\d+)/milli$")

# Fractional (vChip) resource model, grounded in PAPERS.md (Topology-Aware
# Virtualization over Inter-Core Connected NPUs): one chip subdivides into
# MILLI_PER_CHIP milli-units. A pod requests a vChip by carrying FracKey
# (the resource-list-as-config channel, like priority/multislice) with a
# value in [1, MILLI_PER_CHIP): "give me this fraction of ONE chip". The
# device manager advertises per-chip `/milli` capacity keys next to the
# exclusive `/cards` keys; accounting keeps the two mutually exclusive —
# a chip is either whole-held (cards) or carries fractional occupants
# (milli), never both.
MILLI_PER_CHIP = 1000
FracKey = "kubetpu/tpu-milli"


def parse_milli(qty) -> int:
    """Parse a vChip quantity into milli-chips: ``"250m"`` (kube milli
    grammar), ``"0.25"`` / ``0.25`` (chip fraction), or a bare int that
    already IS milli. Raises ValueError outside (0, MILLI_PER_CHIP) —
    whole chips go through the scalar resource, not FracKey."""
    if isinstance(qty, str):
        s = qty.strip()
        if s.endswith("m"):
            m = int(s[:-1])
        else:
            m = int(round(float(s) * MILLI_PER_CHIP))
    elif isinstance(qty, float):
        m = int(round(qty * MILLI_PER_CHIP))
    else:
        m = int(qty)
    if not 0 < m < MILLI_PER_CHIP:
        raise ValueError(
            f"vChip request {qty!r} -> {m} milli-chips is outside "
            f"(0, {MILLI_PER_CHIP}); request whole chips via the scalar "
            f"resource instead"
        )
    return m


def pod_milli(pod_requests) -> int:
    """The pod's fractional (vChip) request in milli-chips, 0 when absent.
    Accepts a PodInfo or a bare requests ResourceList; the stamp value
    may be an int (already milli — the hot-path form) or any
    ``parse_milli`` grammar (``"250m"``, ``"0.25"``, a float) — wire
    clients POST pod requests verbatim, so the documented grammar must
    work here, not only in client-side helpers. Values outside
    (0, MILLI_PER_CHIP) raise ValueError — a malformed stamp must fail
    loudly at the first placement attempt, not silently round."""
    requests = getattr(pod_requests, "requests", pod_requests)
    raw = requests.get(FracKey, 0)
    if not raw:
        return 0
    if isinstance(raw, int):
        if not 0 < raw < MILLI_PER_CHIP:
            raise ValueError(
                f"{FracKey}={raw!r} is outside (0, {MILLI_PER_CHIP})"
            )
        return raw
    return parse_milli(raw)

DEFAULT_SLICE_UID = "slice0"

# Multislice gang pseudo-resources. They ride pod Requests untouched (the
# resource-list-as-config channel, SURVEY.md §5.6) and are defined here —
# not in core.cluster — because both sides of the exec/wire boundary need
# them: the scheduler stamps them at gang placement, the device manager
# reads them at Allocate to emit the libtpu multislice env
# (MEGASCALE_NUM_SLICES / MEGASCALE_SLICE_ID).
#
# - MultisliceKey (input knob): max number of physical slices the gang MAY
#   span; absent/0/1 keeps the single-slice invariant (the default — chips
#   in different slices are DCN, not ICI).
# - GangSlicesKey / GangSliceIdKey (placement artifacts): stamped by
#   schedule_gang on the members of a multislice placement — how many
#   slices the gang actually spans and which sub-gang this pod belongs to.
MultisliceKey = "kubetpu/multislice"
GangSlicesKey = "kubetpu/gang-slices"
GangSliceIdKey = "kubetpu/gang-slice-id"


def slice_resource_key(
    topology_name: str, host_index: int, slice_uid: str = DEFAULT_SLICE_UID
) -> str:
    """The geometry advertisement key for a host of a slice. The slice uid
    distinguishes physically distinct slices of the same topology type —
    chips in different slices are connected over DCN, not ICI, and must
    never be treated as torus-adjacent."""
    return (
        DeviceGroupPrefix
        + "/tpu-slice/"
        + topology_name
        + "/"
        + slice_uid
        + "/"
        + str(host_index)
    )


@dataclass
class NodeMeshState:
    """Geometry of one TPU host-node within its slice."""

    topo: TpuTopology
    host_index: int
    chip_coord: Dict[int, Coord]   # local chip id -> global torus coord
    coord_chip: Dict[Coord, int]   # inverse
    chip_key: Dict[int, str]       # local chip id -> advertised cards key
    # WHOLE-chip availability: coords whose cards key is allocatable AND
    # (Round-18) whose milli key, when advertised, reads full — a chip
    # carrying fractional occupants is invisible to every whole-chip
    # geometry path (fit, fill, preemption feasibility, defrag)
    free: Set[Coord]
    slice_uid: str = DEFAULT_SLICE_UID
    # n -> find_contiguous_block(free, n, topo) result. Valid for this
    # state object's lifetime: the parse memo rebuilds the whole state
    # whenever the advertised resources change, so the cache dies with it.
    # NOTE: cache users must not mutate ``free`` in place.
    fit_cache: Dict[int, object] = None  # type: ignore[assignment]
    # Round-18 fractional capacity: coord -> free milli-chips, for chips
    # that (a) advertise a /milli key (vChip-capable) and (b) are not
    # whole-held via their cards key. A whole-held chip reads 0 here; a
    # pristine vChip-capable chip reads MILLI_PER_CHIP.
    frac_free: Dict[Coord, int] = None  # type: ignore[assignment]
    milli_key: Dict[int, str] = None    # local chip id -> /milli key

    def __post_init__(self) -> None:
        if self.fit_cache is None:
            self.fit_cache = {}
        if self.frac_free is None:
            self.frac_free = {}
        if self.milli_key is None:
            self.milli_key = {}

    def free_milli(self) -> int:
        """Total free capacity of this host in milli-chips: whole-free
        chips count MILLI_PER_CHIP each (via frac_free when vChip-capable,
        directly otherwise); partially-occupied chips contribute their
        remainder — the fractional generalization of ``len(free)``."""
        total = sum(self.frac_free.values())
        covered = {self.chip_coord[l] for l in self.milli_key
                   if l in self.chip_coord}
        total += MILLI_PER_CHIP * sum(
            1 for c in self.free if c not in covered)
        return total

    def best_fit_milli(self, milli: int):
        """THE best-fit rule for a vChip share, in one place: the fitting
        chip with the least remaining capacity wins, ties to the lowest
        local id — so fractional confetti concentrates on already-broken
        chips and pristine chips stay whole for future gangs. Both the
        fit score (TpuScheduler._frac_fit) and the binding fill
        (group_scheduler._fill_fractional) consult this, which is what
        makes the predicate's score and the fill's chip choice provably
        agree. Returns ``(free_milli, local_id, milli_key)`` or None."""
        best = None
        for local, mkey in self.milli_key.items():
            free = self.frac_free.get(self.chip_coord[local], 0)
            if free >= milli and (best is None or (free, local) < best[:2]):
                best = (free, local, mkey)
        return best

    @property
    def slice_name(self) -> str:
        """Identity of the physical slice this host belongs to: hosts share
        a torus frame iff both topology type and slice uid match."""
        return self.topo.name + "/" + self.slice_uid


# Memo for parse_mesh_state — the scheduler hot path re-parses the same
# ResourceList dict for fit, fill, slice grouping and status. The contract:
# every code path that mutates an advertised ResourceList in place MUST call
# invalidate_mesh_state() — today that is core.group_scheduler._account and
# the schedulers' add_node stage-1 translation (add_group_resource mutates
# allocatable before re-assignment); every other change replaces the dict
# object (new id). The fingerprint below is
# belt-and-braces only — (len, scalar) is NOT injective over free-chip sets
# (a take+return netting zero chips restores it), hence the explicit
# invalidation. Entries hold a STRONG reference to the dict so its id
# cannot be recycled while cached; bounded.
_PARSE_MEMO: "dict[int, tuple]" = {}
_PARSE_MEMO_MAX = 4096


def _fingerprint(node_resources: ResourceList):
    return (len(node_resources), node_resources.get(ResourceTPU, -1))


# Round-21 dirty hooks: the incremental fit index (scheduler/fitindex.py)
# needs to know *which node's* advertised list changed, and the memo
# contract above already forces every in-place mutator through
# invalidate_mesh_state — so that call IS the index's invalidation choke
# point. The cluster registers one hook per live allocatable dict
# (id-keyed, like the memo, with the same strong-reference guard against
# id recycling) and re-registers when a lifecycle path replaces the dict
# object. Hooks must be cheap and must not touch mesh state (they fire
# mid-mutation): marking a name dirty is the intended body.
#
# The hook OWNER is held weakly (WeakMethod): the registry must never be
# the thing keeping a dropped Cluster's whole node graph alive. Entries
# whose owner died are purged on the next fire that touches them, plus a
# bulk sweep when the registry grows past a high-water mark (covers
# entries for dicts that are never mutated again — the common case after
# a cluster is discarded, e.g. benches building large throwaway fleets).
_DIRTY_HOOKS: "dict[int, tuple]" = {}
_DIRTY_SWEEP_AT = 4096
_dirty_sweep_at = _DIRTY_SWEEP_AT


def register_dirty_hook(node_resources: ResourceList, method, arg) -> None:
    """Call ``method(arg)`` whenever this exact dict object is invalidated
    (i.e. mutated in place by accounting). One hook per dict; re-register
    replaces. ``method`` must be a bound method — only a weak reference
    to its owner is kept (see the registry comment above)."""
    global _dirty_sweep_at
    if len(_DIRTY_HOOKS) >= _dirty_sweep_at:
        dead = [k for k, v in _DIRTY_HOOKS.items() if v[1]() is None]
        for k in dead:
            del _DIRTY_HOOKS[k]
        _dirty_sweep_at = max(_DIRTY_SWEEP_AT, 2 * len(_DIRTY_HOOKS))
    _DIRTY_HOOKS[id(node_resources)] = (
        node_resources, weakref.WeakMethod(method), arg)


def unregister_dirty_hook(node_resources: ResourceList) -> None:
    _DIRTY_HOOKS.pop(id(node_resources), None)


def invalidate_mesh_state(node_resources: ResourceList) -> None:
    """Drop the memoized geometry for a ResourceList about to be (or just)
    mutated in place. Required by the memo contract above. Also fires the
    registered dirty hook, which is how the fit index and the occupancy
    gauge tracker learn about accounting mutations without any new call
    sites in the accounting code."""
    _PARSE_MEMO.pop(id(node_resources), None)
    hit = _DIRTY_HOOKS.get(id(node_resources))
    if hit is not None and hit[0] is node_resources:
        method = hit[1]()
        if method is None:
            del _DIRTY_HOOKS[id(node_resources)]
        else:
            method(hit[2])


def parse_mesh_state(node_resources: ResourceList) -> Optional[NodeMeshState]:
    """Reconstruct a node's mesh geometry from its (current) allocatable
    ResourceList; None if the node advertises no TPU slice. Memoized on
    (dict identity, free-chip fingerprint)."""
    key = id(node_resources)
    hit = _PARSE_MEMO.get(key)
    fp = _fingerprint(node_resources)
    if hit is not None and hit[0] is node_resources and hit[1] == fp:
        return hit[2]
    state = _parse_mesh_state_uncached(node_resources)
    if len(_PARSE_MEMO) >= _PARSE_MEMO_MAX:
        _PARSE_MEMO.clear()
    _PARSE_MEMO[key] = (node_resources, fp, state)
    return state


def _parse_mesh_state_uncached(node_resources: ResourceList) -> Optional[NodeMeshState]:
    topo: Optional[TpuTopology] = None
    host_index = 0
    slice_uid = DEFAULT_SLICE_UID
    for key in node_resources:
        m = SLICE_KEY_RE.match(key)
        if m:
            topo = TOPOLOGIES.get(m.group(1))
            if m.group(2) is not None:
                slice_uid = m.group(2)
            host_index = int(m.group(3))
            break
    if topo is None:
        return None

    host_coords = topo.host_coords(host_index)
    chip_coord = {i: c for i, c in enumerate(host_coords)}
    coord_chip = {c: i for i, c in chip_coord.items()}

    chip_key: Dict[int, str] = {}
    milli_key: Dict[int, str] = {}
    milli_free: Dict[int, int] = {}  # local id -> advertised free milli
    free: Set[Coord] = set()
    for key, val in node_resources.items():
        m = CHIP_CARDS_RE.match(key)
        if m:
            local = int(m.group(1))
            if local in chip_coord:
                chip_key[local] = key
                if val >= 1:
                    free.add(chip_coord[local])
            continue
        m = CHIP_MILLI_RE.match(key)
        if m:
            local = int(m.group(1))
            if local in chip_coord:
                milli_key[local] = key
                milli_free[local] = int(val)
    # Round-18: a chip with fractional occupants (milli below full) is
    # not whole-free, and a whole-held chip (cards gone) has no
    # fractional capacity — the two allocation grammars are exclusive.
    frac_free: Dict[Coord, int] = {}
    for local, mkey in milli_key.items():
        coord = chip_coord[local]
        if coord in free:
            frac_free[coord] = milli_free[local]
            if milli_free[local] < MILLI_PER_CHIP:
                free.discard(coord)
    return NodeMeshState(
        topo=topo,
        host_index=host_index,
        chip_coord=chip_coord,
        coord_chip=coord_chip,
        chip_key=chip_key,
        free=free,
        slice_uid=slice_uid,
        frac_free=frac_free,
        milli_key=milli_key,
    )
