"""Per-node ICI mesh state, reconstructed from advertised resources.

The TPU device manager advertises one geometry key per node,

    resource/group/tpu-slice/<topology-name>/<host-index>: 1

alongside the per-chip grouped card keys. Chip local id <-> torus coordinate
is a fixed bijection (row-major within the host's block), so the scheduler
can reconstruct full geometry from the ResourceList alone — the source of
truth is always the advertised resources (the reference's stateless
rebuild-from-probe contract, SURVEY.md §5.4). ``parse_mesh_state`` keeps a
derived-data memo purely as a hot-path optimization; its invalidation
contract is documented at the memo below, and the single in-place mutator of
advertised lists (core accounting) invalidates explicitly. Multi-host slices
share <topology-name>; each host advertises its own <host-index>, giving
gang placement a global coordinate frame.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, Optional, Set

from kubetpu.api.types import DeviceGroupPrefix, ResourceList
from kubetpu.plugintypes.mesh import TOPOLOGIES, Coord, TpuTopology
from kubetpu.plugintypes.treetypes import ResourceTPU

# resource/group/tpu-slice/<topology-name>/<slice-uid>/<host-index>
# (legacy 3-segment form without the slice uid is accepted: a cluster with a
# single anonymous slice per topology)
SLICE_KEY_RE = re.compile(
    re.escape(DeviceGroupPrefix) + r"/tpu-slice/([^/]+)(?:/([^/]+))?/(\d+)$"
)
# any grouped per-chip cards key: .../tpu/<localid>/cards
CHIP_CARDS_RE = re.compile(r".*/tpu/(\d+)/cards$")

DEFAULT_SLICE_UID = "slice0"

# Multislice gang pseudo-resources. They ride pod Requests untouched (the
# resource-list-as-config channel, SURVEY.md §5.6) and are defined here —
# not in core.cluster — because both sides of the exec/wire boundary need
# them: the scheduler stamps them at gang placement, the device manager
# reads them at Allocate to emit the libtpu multislice env
# (MEGASCALE_NUM_SLICES / MEGASCALE_SLICE_ID).
#
# - MultisliceKey (input knob): max number of physical slices the gang MAY
#   span; absent/0/1 keeps the single-slice invariant (the default — chips
#   in different slices are DCN, not ICI).
# - GangSlicesKey / GangSliceIdKey (placement artifacts): stamped by
#   schedule_gang on the members of a multislice placement — how many
#   slices the gang actually spans and which sub-gang this pod belongs to.
MultisliceKey = "kubetpu/multislice"
GangSlicesKey = "kubetpu/gang-slices"
GangSliceIdKey = "kubetpu/gang-slice-id"


def slice_resource_key(
    topology_name: str, host_index: int, slice_uid: str = DEFAULT_SLICE_UID
) -> str:
    """The geometry advertisement key for a host of a slice. The slice uid
    distinguishes physically distinct slices of the same topology type —
    chips in different slices are connected over DCN, not ICI, and must
    never be treated as torus-adjacent."""
    return (
        DeviceGroupPrefix
        + "/tpu-slice/"
        + topology_name
        + "/"
        + slice_uid
        + "/"
        + str(host_index)
    )


@dataclass
class NodeMeshState:
    """Geometry of one TPU host-node within its slice."""

    topo: TpuTopology
    host_index: int
    chip_coord: Dict[int, Coord]   # local chip id -> global torus coord
    coord_chip: Dict[Coord, int]   # inverse
    chip_key: Dict[int, str]       # local chip id -> advertised cards key
    free: Set[Coord]               # coords whose cards key is allocatable
    slice_uid: str = DEFAULT_SLICE_UID
    # n -> find_contiguous_block(free, n, topo) result. Valid for this
    # state object's lifetime: the parse memo rebuilds the whole state
    # whenever the advertised resources change, so the cache dies with it.
    # NOTE: cache users must not mutate ``free`` in place.
    fit_cache: Dict[int, object] = None  # type: ignore[assignment]

    def __post_init__(self) -> None:
        if self.fit_cache is None:
            self.fit_cache = {}

    @property
    def slice_name(self) -> str:
        """Identity of the physical slice this host belongs to: hosts share
        a torus frame iff both topology type and slice uid match."""
        return self.topo.name + "/" + self.slice_uid


# Memo for parse_mesh_state — the scheduler hot path re-parses the same
# ResourceList dict for fit, fill, slice grouping and status. The contract:
# every code path that mutates an advertised ResourceList in place MUST call
# invalidate_mesh_state() — today that is core.group_scheduler._account and
# the schedulers' add_node stage-1 translation (add_group_resource mutates
# allocatable before re-assignment); every other change replaces the dict
# object (new id). The fingerprint below is
# belt-and-braces only — (len, scalar) is NOT injective over free-chip sets
# (a take+return netting zero chips restores it), hence the explicit
# invalidation. Entries hold a STRONG reference to the dict so its id
# cannot be recycled while cached; bounded.
_PARSE_MEMO: "dict[int, tuple]" = {}
_PARSE_MEMO_MAX = 4096


def _fingerprint(node_resources: ResourceList):
    return (len(node_resources), node_resources.get(ResourceTPU, -1))


def invalidate_mesh_state(node_resources: ResourceList) -> None:
    """Drop the memoized geometry for a ResourceList about to be (or just)
    mutated in place. Required by the memo contract above."""
    _PARSE_MEMO.pop(id(node_resources), None)


def parse_mesh_state(node_resources: ResourceList) -> Optional[NodeMeshState]:
    """Reconstruct a node's mesh geometry from its (current) allocatable
    ResourceList; None if the node advertises no TPU slice. Memoized on
    (dict identity, free-chip fingerprint)."""
    key = id(node_resources)
    hit = _PARSE_MEMO.get(key)
    fp = _fingerprint(node_resources)
    if hit is not None and hit[0] is node_resources and hit[1] == fp:
        return hit[2]
    state = _parse_mesh_state_uncached(node_resources)
    if len(_PARSE_MEMO) >= _PARSE_MEMO_MAX:
        _PARSE_MEMO.clear()
    _PARSE_MEMO[key] = (node_resources, fp, state)
    return state


def _parse_mesh_state_uncached(node_resources: ResourceList) -> Optional[NodeMeshState]:
    topo: Optional[TpuTopology] = None
    host_index = 0
    slice_uid = DEFAULT_SLICE_UID
    for key in node_resources:
        m = SLICE_KEY_RE.match(key)
        if m:
            topo = TOPOLOGIES.get(m.group(1))
            if m.group(2) is not None:
                slice_uid = m.group(2)
            host_index = int(m.group(3))
            break
    if topo is None:
        return None

    host_coords = topo.host_coords(host_index)
    chip_coord = {i: c for i, c in enumerate(host_coords)}
    coord_chip = {c: i for i, c in chip_coord.items()}

    chip_key: Dict[int, str] = {}
    free: Set[Coord] = set()
    for key, val in node_resources.items():
        m = CHIP_CARDS_RE.match(key)
        if m:
            local = int(m.group(1))
            if local in chip_coord:
                chip_key[local] = key
                if val >= 1:
                    free.add(chip_coord[local])
    return NodeMeshState(
        topo=topo,
        host_index=host_index,
        chip_coord=chip_coord,
        coord_chip=coord_chip,
        chip_key=chip_key,
        free=free,
        slice_uid=slice_uid,
    )
