"""Auto topology generation: pick the best cached node shape and rewrite a
pod's device requests into that shape with synthetic indices.

Reference: ``gpuschedulerplugin/gpu.go:247-324`` — ``assignGPUs`` (greedy
left-to-right tree walk emitting e.g.
``resource/group/gpugrp1/0/gpugrp0/0/gpu/0/cards``), ``translateToTree``
(strip old per-device requests, append the synthesized ones), and
``ConvertToBestGPURequests`` (pod device count = Σ running, max init).

The synthesized key grammar must match the reference byte-for-byte (modulo
the device-class segment names) — it is the wire format the group scheduler
bin-packs against (SURVEY.md §7 "the translation grammar is subtle").
"""

from __future__ import annotations

from typing import List, Optional

from kubetpu.api import utils
from kubetpu.api.types import ContainerInfo, DeviceGroupPrefix, PodInfo, ResourceList
from kubetpu.plugintypes import SortedTreeNode, log_tree_node
from kubetpu.scheduler.deviceclass import DeviceClass
from kubetpu.scheduler.treecache import NodeTreeCache


def assign_devices(
    node: SortedTreeNode,
    prefix: str,
    resource_grp: str,
    resource: str,
    suffix: str,
    level: int,
    num_left: List[int],
) -> ResourceList:
    """Greedy left-to-right tree walk emitting topology-shaped request keys
    with synthetic indices (reference assignGPUs, gpu.go:247-271).

    *num_left* is a 1-element list standing in for the reference's ``*int``.
    """
    res_list: ResourceList = {}
    if level == 0:
        to_take = min(node.val, num_left[0])
        for i in range(to_take):
            res_list[prefix + "/" + resource + "/" + str(i) + "/" + suffix] = 1
        num_left[0] -= to_take
    else:
        for i, child in enumerate(node.children):
            new_prefix = prefix + str(level - 1) + "/" + str(i)
            if level - 1 != 0:
                new_prefix += "/" + resource_grp
            res_list.update(
                assign_devices(child, new_prefix, resource_grp, resource, suffix, level - 1, num_left)
            )
    return res_list


# assign_devices output depends ONLY on (tree shape, device class, count) —
# memoize it so the per-(pod x node) predicate loop doesn't re-synthesize
# identical key sets for every node sharing a shape (the reference's shape
# dedup cache exists for exactly this reason, gpu.go:163-245; at 500+ nodes
# the re-synthesis dominates the <100 ms p50 budget). Entries hold a strong
# reference to the tree so its id cannot be recycled while cached; bounded.
_ASSIGN_MEMO: dict = {}
_ASSIGN_MEMO_MAX = 4096


def _assigned_for(dc: DeviceClass, tree: SortedTreeNode, count: int) -> ResourceList:
    key = (id(tree), dc.grp_prefix, count)
    hit = _ASSIGN_MEMO.get(key)
    if hit is not None and hit[0] is tree:
        return hit[1]
    num_left = [count]
    res_list = assign_devices(
        tree,
        DeviceGroupPrefix + "/" + dc.grp_prefix,
        dc.grp_prefix,
        dc.base,
        "cards",
        2,
        num_left,
    )
    if len(_ASSIGN_MEMO) >= _ASSIGN_MEMO_MAX:
        _ASSIGN_MEMO.clear()
    _ASSIGN_MEMO[key] = (tree, res_list)
    return res_list


def translate_to_tree(dc: DeviceClass, node: SortedTreeNode, cont: ContainerInfo) -> None:
    """Strip the container's existing per-device topology requests and
    append ones synthesized against *node* (reference translateToTree,
    gpu.go:273-291)."""
    cont.dev_requests = {
        k: v for k, v in cont.dev_requests.items() if not dc.any_base_re.match(k)
    }
    count = int(cont.requests.get(dc.resource_name, 0))
    cont.dev_requests.update(_assigned_for(dc, node, count))


def convert_to_best_requests(
    dc: DeviceClass,
    cache: NodeTreeCache,
    pod_info: PodInfo,
    best_tree: Optional[SortedTreeNode] = None,
) -> bool:
    """Rewrite every container against the best cached shape holding the
    pod's total device count: running containers sum, init containers max
    (reference ConvertToBestGPURequests, gpu.go:294-324)."""
    num = 0
    for cont in pod_info.running_containers.values():
        num += cont.requests.get(dc.resource_name, 0)
    for cont in pod_info.init_containers.values():
        num = max(num, cont.requests.get(dc.resource_name, 0))
    if best_tree is None:
        best_tree = cache.find_best_tree(int(num))
    if best_tree is None:
        return False
    utils.logf(5, "Best tree")
    log_tree_node(5, best_tree)
    for key in utils.sorted_string_keys(pod_info.running_containers):
        translate_to_tree(dc, best_tree, pod_info.running_containers[key])
    for key in utils.sorted_string_keys(pod_info.init_containers):
        translate_to_tree(dc, best_tree, pod_info.init_containers[key])
    return True
