"""Topology-aware scheduler plugins (analog of reference
``gpuschedulerplugin``): request translation, node topology-shape caching,
auto topology generation, and the TPU/GPU DeviceScheduler implementations.
"""

from kubetpu.scheduler.deviceclass import GPU, TPU, DeviceClass
from kubetpu.scheduler.gpu_scheduler import GpuScheduler, GPUTopologyGeneration
from kubetpu.scheduler.tpu_scheduler import TpuScheduler, TPUTopologyGeneration
from kubetpu.scheduler.treecache import NodeTreeCache, add_to_node, compute_tree_score

__all__ = [
    "GPU",
    "TPU",
    "DeviceClass",
    "GpuScheduler",
    "GPUTopologyGeneration",
    "TpuScheduler",
    "TPUTopologyGeneration",
    "NodeTreeCache",
    "add_to_node",
    "compute_tree_score",
]
