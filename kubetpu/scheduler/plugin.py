"""Scheduler plugin entry shims.

Analog of the reference's ``--buildmode=plugin`` entry files
(``gpuschedulerplugin/plugin/gpuscheduler.go:8-11``): the factory symbols the
core looks up after loading a plugin module via
``kubetpu.api.devicescheduler.create_device_scheduler_from_plugin``.
"""

from __future__ import annotations

from kubetpu.api.devicescheduler import DeviceScheduler
from kubetpu.scheduler.gpu_scheduler import GpuScheduler
from kubetpu.scheduler.tpu_scheduler import TpuScheduler


def create_device_scheduler_plugin() -> DeviceScheduler:
    """The TPU scheduler factory (the default plugin this repo ships)."""
    return TpuScheduler()


def create_gpu_device_scheduler_plugin() -> DeviceScheduler:
    """The NVIDIA scheduler factory, for heterogeneous clusters."""
    return GpuScheduler()
