"""The TPU topology-aware scheduler plugin.

Mirrors the reference's ``NvidiaGPUScheduler`` surface
(``gpuschedulerplugin/gpu_scheduler.go:21-71``) with the TPU generalization
BASELINE.json names: placements are ranked by **ICI-mesh adjacency** — the
fit score is the contiguity the pod's chips can achieve on this node's free
torus coordinates — instead of the tree-depth score alone. Translation and
the tree cache still speak the reference's grouped-key grammar, so GPU-style
nodes and TPU nodes coexist (BASELINE config 5).
"""

from __future__ import annotations

from typing import Dict, Tuple

from kubetpu.api import utils
from kubetpu.api.devicescheduler import DeviceScheduler, FitResult, PredicateFailureReason
from kubetpu.api.types import DeviceGroupPrefix, NodeInfo, PodInfo
from kubetpu.obs import trace as obs_trace
from kubetpu.plugintypes.mesh import find_contiguous_block
from kubetpu.scheduler import meshstate
from kubetpu.scheduler.deviceclass import TPU
from kubetpu.scheduler.translate import (
    pod_wants_device,
    prepare_pod,
    translate_device_resources,
    translate_pod_device_resources,
)
from kubetpu.scheduler.treecache import NodeTreeCache

# Per-pod auto-topology knob, rides the pod's Requests untouched (reference
# GPUTopologyGeneration = "gpu/gpu-generate-topology", gpu_scheduler.go:12-15).
TPUTopologyGeneration = TPU.topology_gen_key


class TpuScheduler(DeviceScheduler):
    """DeviceScheduler for the TPU family with ICI-adjacency ranking."""

    def __init__(self) -> None:
        self._cache = NodeTreeCache(TPU.grp_prefix, "cards", levels=1)

    # (topology name, host index, n) -> find_contiguous_block result for a
    # PRISTINE host (every chip free). Cold-start cost on a large cluster is
    # the first sweep of a new gang size running the geometry search once
    # per node; pristine hosts of the same topology+host-index are
    # byte-identical searches, so one result serves them all (a 512-node
    # v5e-256 cluster has 32 distinct host indices, not 512 searches).
    # Results are shared read-only — the fit-cache contract above already
    # forbids mutating them. Class-level: survives scheduler instances,
    # bounded.
    _pristine_fit: Dict[Tuple[str, int, int], object] = {}
    _PRISTINE_FIT_MAX = 8192

    def _pristine_or_search(self, state, n: int):
        if len(state.free) != len(state.chip_coord):
            return find_contiguous_block(state.free, n, state.topo)
        key = (state.topo.name, state.host_index, n)
        hit = self._pristine_fit.get(key)
        if hit is None:
            hit = find_contiguous_block(state.free, n, state.topo)
            if len(self._pristine_fit) >= self._PRISTINE_FIT_MAX:
                self._pristine_fit.clear()
            self._pristine_fit[key] = hit
        return hit

    # -- node lifecycle -----------------------------------------------------

    def add_node(self, node_name: str, node_info: NodeInfo) -> None:
        """Normalize the node's allocatable to the 2-level grouped form by
        translating against a synthetic fully-grouped 1-device list, then
        cache its topology shape (reference AddNode trick,
        gpu_scheduler.go:21-28). Spanned (``tpu.add_node``): registration
        storms show up in the trace timeline, node by node."""
        with obs_trace.span("tpu.add_node", node=node_name):
            self._add_node_inner(node_name, node_info)

    def _add_node_inner(self, node_name: str, node_info: NodeInfo) -> None:
        synthetic = {
            DeviceGroupPrefix + "/tpugrp1/A/tpugrp0/B/tpu/TPU0/cards": 1,
        }
        # The translation below mutates node_info.allocatable in place
        # (add_group_resource) before re-assigning it — drop any memoized
        # geometry keyed on the old dict identity (meshstate memo contract).
        meshstate.invalidate_mesh_state(node_info.allocatable)
        node_info.allocatable = translate_device_resources(
            TPU,
            node_info.kube_alloc.get(TPU.resource_name, 0),
            synthetic,
            node_info.allocatable,
        )
        utils.logf(4, "AllocAddNode: %s", node_info.allocatable)
        self._cache.add_resources(node_name, node_info.allocatable)

    def remove_node(self, node_name: str) -> None:
        self._cache.remove_node(node_name)

    # -- scheduling ---------------------------------------------------------

    def _mesh_fit(self, node_info: NodeInfo, n: int) -> Tuple[bool, float]:
        """(fits, ICI score) of placing an n-chip gang on this node's free
        coords — the ICI-mesh generalization of tree ranking."""
        state = meshstate.parse_mesh_state(node_info.allocatable)
        if state is None:
            # Not a TPU-mesh node (e.g. GPU-style grouping): neutral score,
            # scalar capacity decides.
            free = node_info.allocatable.get(TPU.resource_name, 0)
            return free >= n, 0.0
        if n == 0:
            # A pod wanting no TPUs must not be steered TOWARD mesh nodes
            # (and 0.0 keeps perfect_score's bound provably-best).
            return True, 0.0
        # Placement depends only on (free set, n, topo) — all captured by
        # the state object, which is rebuilt whenever the advertised
        # resources change, so caching per-n on it is sound and saves the
        # per-(pod x node) geometry search in the predicate loop.
        if n in state.fit_cache:
            placed = state.fit_cache[n]
        else:
            placed = self._pristine_or_search(state, n)
            state.fit_cache[n] = placed
        if placed is None:
            return False, 0.0
        _, score = placed
        return True, score

    def _frac_fit(self, node_info: NodeInfo, want: int, frac: int) -> FitResult:
        """Fractional (vChip, Round-18) placement: fits iff some chip has
        ``frac`` free milli-chips; the score is the post-placement
        occupancy of the BEST-FIT chip (tightest fitting remainder), so
        the predicate sweep bin-packs — a node whose partially-filled
        chip the vChip completes scores 1.0 (the perfect_score bound),
        while breaking a pristine chip scores only frac/1000. That
        ordering IS the anti-fragmentation policy: small replicas
        concentrate on already-broken chips and whole chips stay free
        for future whole-chip gangs. No translation stage — the fill
        binds the chip's ``/milli`` key directly."""
        if want > 0:
            reason = PredicateFailureReason(
                resource_name=meshstate.FracKey,
                requested=frac,
                capacity=0,
                message="a pod cannot mix whole-chip and vChip requests",
            )
            return False, [reason], 0.0
        state = meshstate.parse_mesh_state(node_info.allocatable)
        best = state.best_fit_milli(frac) if state is not None else None
        if best is None:
            reason = PredicateFailureReason(
                resource_name=meshstate.FracKey,
                requested=frac,
                capacity=max(state.frac_free.values(), default=0)
                if state is not None else 0,
                message="no chip with enough free fractional capacity"
                if state is not None
                else "vChips need mesh geometry (no tpu-slice advertised)",
            )
            return False, [reason], 0.0
        # score from the SAME chip the fill will bind (best_fit_milli is
        # the shared best-fit rule): its post-placement occupancy.
        score = (meshstate.MILLI_PER_CHIP - (best[0] - frac)) / float(
            meshstate.MILLI_PER_CHIP)
        return True, [], score

    def pod_fits_device(
        self, node_info: NodeInfo, pod_info: PodInfo, fill_allocate_from: bool
    ) -> FitResult:
        """Translate the pod's requests (reference PodFitsDevice,
        gpu_scheduler.go:34-44), then rank by achievable ICI contiguity.

        Rejection is ordered cheapest-first — the predicate runs per
        (pod x node) and failing nodes dominate large clusters (SURVEY.md
        §7 <100 ms p50): (1) pod-memoized request shaping (prepare_pod —
        pod-invariant, computed once per sweep, not once per node); (2)
        scalar free-count check; (3) mesh geometry (per-n fit cache on the
        node's mesh state); (4) only for nodes that can actually host the
        pod, the grouped-key translation."""
        want, has_base = prepare_pod(TPU, pod_info)
        frac = meshstate.pod_milli(pod_info)
        if frac > 0:
            return self._frac_fit(node_info, want, frac)
        if want == 0 and not has_base:
            # No TPUs requested and no stale TPU keys to strip: translation
            # would be a no-op — skip it (GPU-only pods must not pay the
            # TPU translation on every node).
            return True, [], 0.0
        if want > 0 and node_info.allocatable.get(TPU.resource_name, 0) < want:
            reason = PredicateFailureReason(
                resource_name=TPU.resource_name,
                requested=want,
                capacity=node_info.allocatable.get(TPU.resource_name, 0),
                message="insufficient free TPU chips",
            )
            return False, [reason], 0.0
        fits, score = self._mesh_fit(node_info, want)
        if not fits:
            # fragmented node: reject on cached geometry BEFORE paying the
            # translation — the saturated/fragmented full-sweep worst case
            # is built from exactly these rejections
            reason = PredicateFailureReason(
                resource_name=TPU.resource_name,
                requested=want,
                capacity=node_info.allocatable.get(TPU.resource_name, 0),
                message="insufficient free ICI-contiguous TPU chips",
            )
            return False, [reason], 0.0
        err, found = translate_pod_device_resources(TPU, self._cache, node_info, pod_info)
        if err is not None or not found:
            return False, [], 0.0
        # (translation never changes the scalar count: want still holds)
        return True, [], score

    def pod_allocate(self, node_info: NodeInfo, pod_info: PodInfo) -> None:
        # spanned at ALLOCATE granularity only — the pod_fits_device
        # predicate runs per (pod x node) and must stay span-free (the
        # obs discipline: spans per operation, histograms per loop)
        with obs_trace.span("tpu.pod_allocate", node=node_info.name,
                            pod=pod_info.name):
            err, found = translate_pod_device_resources(
                TPU, self._cache, node_info, pod_info)
            if err is not None:
                raise RuntimeError(err)
            if not found:
                raise RuntimeError(
                    "translate_pod_device_resources found no translation")

    def take_pod_resources(self, node_info: NodeInfo, pod_info: PodInfo) -> None:
        """No-op: the core harness owns usage accounting (reference
        gpu_scheduler.go:57-59 is likewise a no-op)."""

    def return_pod_resources(self, node_info: NodeInfo, pod_info: PodInfo) -> None:
        """No-op (reference gpu_scheduler.go:61-63)."""

    def perfect_score(self, pod_info: PodInfo):
        """ICI contiguity is capped at 1.0 (a perfect rectangular block);
        a vChip's bin-pack score is likewise capped at 1.0 (an exact-fit
        chip); pods requesting neither always score 0.0 (see _mesh_fit)."""
        if pod_wants_device(TPU, pod_info):
            return 1.0
        return 1.0 if meshstate.pod_milli(pod_info) > 0 else 0.0

    def get_name(self) -> str:
        return "tpu"

    def using_group_scheduler(self) -> bool:
        """Delegate bin-packing/AllocateFrom fill to the core group scheduler
        (reference gpu_scheduler.go:69-71; kubetpu's is kubetpu.core)."""
        return True

    # -- diagnostics --------------------------------------------------------

    def cache_shapes(self):
        return self._cache.shapes()
