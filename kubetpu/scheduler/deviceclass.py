"""Device classes: the naming scheme a device family uses in the grouped
resource grammar.

The reference hardcodes the NVIDIA names ("gpu", "gpugrp0", "gpugrp1",
"nvidia.com/gpu", "gpu/gpu-generate-topology") throughout
``gpuschedulerplugin/gpu.go``; kubetpu parameterizes them so the identical
translation/tree machinery serves both the TPU and NVIDIA device families in
a heterogeneous cluster (BASELINE config 5).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Pattern

from kubetpu.api.types import DeviceGroupPrefix
from kubetpu.plugintypes import ResourceGPU, ResourceTPU


@dataclass(frozen=True)
class DeviceClass:
    """Names a device family uses in resource keys.

    Grouped keys look like
    ``resource/group/<grp1>/<j>/<grp0>/<i>/<base>/<id>/cards``.
    """

    resource_name: str  # scalar resource, e.g. "kubedevice/tpu"
    base: str           # leaf segment, e.g. "tpu"
    grp0: str           # level-0 group segment, e.g. "tpugrp0"
    grp1: str           # level-1 group segment, e.g. "tpugrp1"
    grp_prefix: str     # common group-segment prefix, e.g. "tpugrp"
    topology_gen_key: str  # per-pod auto-topology knob pseudo-resource

    # Precompiled hot-path regexes (the reference recompiles these inside
    # per-call functions, gpu.go:18,131,275 — flagged as a p50 hazard in
    # SURVEY.md §7; kubetpu compiles once per device class).
    cards_re: Pattern = field(init=False, repr=False, compare=False)
    any_base_re: Pattern = field(init=False, repr=False, compare=False)
    alloc_re: Pattern = field(init=False, repr=False, compare=False)
    # Round-18 vChips: the fractional sibling of alloc_re — a fully
    # grouped per-chip /milli key in an AllocateFrom value.
    milli_alloc_re: Pattern = field(init=False, repr=False, compare=False)

    def __post_init__(self):
        object.__setattr__(
            self,
            "cards_re",
            # reference: regexp `<DeviceGroupPrefix>.*/gpu/(.*?)/cards` (gpu.go:18)
            re.compile(re.escape(DeviceGroupPrefix) + r".*/" + re.escape(self.base) + r"/(.*?)/cards"),
        )
        object.__setattr__(
            self,
            "any_base_re",
            # reference: regexp `.*/gpu/.*` (gpu.go:275) — strips old topology requests
            re.compile(r".*/" + re.escape(self.base) + r"/.*"),
        )
        object.__setattr__(
            self,
            "alloc_re",
            # reference: regexp `<prefix>/gpugrp1/.*/gpugrp0/.*/gpu/(.*?)/cards`
            # (nvidia_gpu_manager.go:225)
            re.compile(
                re.escape(DeviceGroupPrefix)
                + "/" + re.escape(self.grp1) + "/.*/"
                + re.escape(self.grp0) + "/.*/"
                + re.escape(self.base) + "/(.*?)/cards"
            ),
        )
        object.__setattr__(
            self,
            "milli_alloc_re",
            re.compile(
                re.escape(DeviceGroupPrefix)
                + "/" + re.escape(self.grp1) + "/.*/"
                + re.escape(self.grp0) + "/.*/"
                + re.escape(self.base) + "/(.*?)/milli"
            ),
        )


# The TPU device family (BASELINE.json: pod specs request "kubedevice/tpu").
TPU = DeviceClass(
    resource_name=ResourceTPU,
    base="tpu",
    grp0="tpugrp0",
    grp1="tpugrp1",
    grp_prefix="tpugrp",
    topology_gen_key="tpu/tpu-generate-topology",
)

# The NVIDIA device family (reference names, gpu_scheduler.go:12-15).
GPU = DeviceClass(
    resource_name=ResourceGPU,
    base="gpu",
    grp0="gpugrp0",
    grp1="gpugrp1",
    grp_prefix="gpugrp",
    topology_gen_key="gpu/gpu-generate-topology",
)
