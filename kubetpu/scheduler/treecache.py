"""Node-topology tree cache: parse a node's grouped resources into a sorted
tree, score it, and dedupe identical topology *shapes* across nodes.

Reference: ``gpuschedulerplugin/gpu.go:129-245`` — ``addToNode`` (regex parse
of key structure, two levels), ``computeTreeScore`` (Σ val*level/numChild:
deeper/denser grouping ⇒ higher score), the shape-dedup cache
(``NodeCacheMap``/``NodeLocationMap``) and ``findBestTreeInCache``.

Differences from the reference, by design:

- The cache is an *instance*, not package-global state: the reference's
  globals are unsynchronized and safe only because the external core calls
  plugins single-threaded (SURVEY.md §5.2). Here a lock makes the contract
  explicit.
- Regexes are compiled once per (prefix, suffix, level) instead of per call
  (reference compiles in the hot path, gpu.go:131 — SURVEY.md §7 flags this
  for the <100 ms p50 target).
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from kubetpu.api import utils
from kubetpu.api.types import ResourceList
from kubetpu.plugintypes import (
    SortedTreeNode,
    add_node_to_sorted_tree_node,
    compare_tree_node,
)

_LEVEL_RE_CACHE: Dict[Tuple[str, str, int], "re.Pattern[str]"] = {}


def _level_re(partition_prefix: str, suffix: str, level: int) -> "re.Pattern[str]":
    key = (partition_prefix, suffix, level)
    pat = _LEVEL_RE_CACHE.get(key)
    if pat is None:
        # reference: `.*/<prefix><level>/(.*?)/.*/<suffix>` (gpu.go:131)
        pat = re.compile(
            r".*/" + re.escape(partition_prefix) + str(level) + r"/(.*?)/.*/" + re.escape(suffix)
        )
        _LEVEL_RE_CACHE[key] = pat
    return pat


def add_to_node(
    node: Optional[SortedTreeNode],
    node_resources: ResourceList,
    partition_prefix: str,
    suffix: str,
    partition_level: int,
) -> SortedTreeNode:
    """Parse grouped resource keys into a sorted tree, one recursion per
    hierarchy level (reference addToNode, gpu.go:129-161)."""
    pat = _level_re(partition_prefix, suffix, partition_level)
    child_map: Dict[str, ResourceList] = {}
    total_len = 0
    for resource_key in utils.sorted_string_keys(node_resources):
        m = pat.match(resource_key)
        if m:
            child_map.setdefault(m.group(1), {})[resource_key] = node_resources[resource_key]
            total_len += 1
    if node is None:
        node = SortedTreeNode(val=total_len)
    for sub_key in utils.sorted_string_keys(child_map):
        sub_map = child_map[sub_key]
        child = SortedTreeNode(val=len(sub_map))
        if partition_level > 0:
            add_to_node(child, sub_map, partition_prefix, suffix, partition_level - 1)
            child.score = compute_tree_score(child)
        add_node_to_sorted_tree_node(node, child)
    return node


def _compute_tree_score_at_level(node: SortedTreeNode, level: int, num_child: int) -> float:
    score = float(node.val * level) / float(num_child) if num_child else 0.0
    for child in node.children:
        score += _compute_tree_score_at_level(child, level + 1, len(node.children))
    return score


def compute_tree_score(node: SortedTreeNode) -> float:
    """Σ val*level/numChild over the tree — deeper/denser grouping scores
    higher (reference computeTreeScore, gpu.go:180-190)."""
    return _compute_tree_score_at_level(node, 0, len(node.children))


@dataclass
class _TreeInfo:
    list_of_nodes: Set[str] = field(default_factory=set)
    tree_score: float = 0.0


class NodeTreeCache:
    """Shape-dedup cache of node topology trees (reference NodeCacheMap /
    NodeLocationMap + add/remove/find, gpu.go:163-245)."""

    def __init__(self, partition_prefix: str, suffix: str = "cards", levels: int = 1):
        self._partition_prefix = partition_prefix
        self._suffix = suffix
        self._levels = levels
        self._lock = threading.Lock()
        # id(tree) -> (tree, info); trees are compared structurally.
        self._cache: Dict[int, Tuple[SortedTreeNode, _TreeInfo]] = {}
        self._node_location: Dict[str, SortedTreeNode] = {}

    def _remove_locked(self, node_name: str, location: Optional[SortedTreeNode]) -> None:
        if location is None:
            return
        entry = self._cache.get(id(location))
        if entry is None:
            return
        entry[1].list_of_nodes.discard(node_name)
        if not entry[1].list_of_nodes:
            del self._cache[id(location)]

    def add_resources(self, node_name: str, node_resources: ResourceList) -> None:
        """Parse + dedupe a node's topology shape (reference
        AddResourcesToNodeTreeCache, gpu.go:192-224)."""
        if not node_resources:
            return
        tree = add_to_node(None, node_resources, self._partition_prefix, self._suffix, self._levels)
        with self._lock:
            location = self._node_location.get(node_name)
            if compare_tree_node(tree, location):
                return
            self._remove_locked(node_name, location)
            for cached_tree, info in self._cache.values():
                if compare_tree_node(tree, cached_tree):
                    info.list_of_nodes.add(node_name)
                    self._node_location[node_name] = cached_tree
                    return
            info = _TreeInfo(list_of_nodes={node_name}, tree_score=compute_tree_score(tree))
            self._cache[id(tree)] = (tree, info)
            self._node_location[node_name] = tree

    def remove_node(self, node_name: str) -> None:
        """Reference RemoveNodeFromNodeTreeCache (gpu.go:226-230)."""
        with self._lock:
            self._remove_locked(node_name, self._node_location.get(node_name))
            self._node_location.pop(node_name, None)

    def find_best_tree(self, num: int) -> Optional[SortedTreeNode]:
        """Highest-scoring cached shape with at least *num* leaves
        (reference findBestTreeInCache, gpu.go:232-245)."""
        best: Optional[SortedTreeNode] = None
        best_score = 0.0
        with self._lock:
            for tree, info in self._cache.values():
                if tree.val >= num and info.tree_score > best_score:
                    best, best_score = tree, info.tree_score
        return best

    def node_tree(self, node_name: str) -> Optional[SortedTreeNode]:
        """The cached shape a node currently maps to."""
        with self._lock:
            return self._node_location.get(node_name)

    def shapes(self) -> List[Tuple[SortedTreeNode, Set[str], float]]:
        """Snapshot of (tree, nodes sharing it, score) for diagnostics."""
        with self._lock:
            return [(t, set(i.list_of_nodes), i.tree_score) for t, i in self._cache.values()]
