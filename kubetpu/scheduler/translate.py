"""Request translation: rewrite a container's device requests into the
hierarchical, topology-shaped form a node advertises.

Reference: the 3-stage rewrite of ``gpuschedulerplugin/gpu.go:16-127`` —
(1) expand the scalar device count into per-card keys, guarded by "does the
node advertise grouped cards"; (2) wrap into level-0 groups; (3) wrap into
level-1 groups. Plus ``SetGPUReqs`` (max-merge of kube-native and
device-native counts) and the per-pod orchestrator
``TranslatePodGPUResources`` with the auto-topology knob.
"""

from __future__ import annotations

from typing import Optional, Tuple

from kubetpu.api import utils
from kubetpu.api.resource import translate_resource
from kubetpu.api.types import ContainerInfo, NodeInfo, PodInfo, ResourceList, add_group_resource
from kubetpu.scheduler.deviceclass import DeviceClass
from kubetpu.scheduler.topology_gen import convert_to_best_requests
from kubetpu.scheduler.treecache import NodeTreeCache


def prepare_pod(dc: DeviceClass, pod_info: PodInfo):
    """Per-(pod, device-class) request shaping + counts, memoized ON the
    pod object: the predicate sweep calls pod_fits_device once per node,
    but ``set_device_reqs``, the device count, and the stale-key scan
    depend only on the pod — recomputing them per node is the dominant
    warm-sweep cost at 1000+ nodes (BASELINE.md "~16 us/node").

    Returns ``(want, has_base_keys)``. A fingerprint of the scalar request
    values guards the memo: a caller that mutates counts between fit calls
    (tests do) gets a recompute, not stale answers. ``set_device_reqs`` is
    idempotent, so re-running it on a memo miss is safe. (``copy()``
    rebuilds from fields, so the memo never leaks across pod copies.)
    """
    rn = dc.resource_name
    fp = tuple(
        (cname, cont.requests.get(rn), cont.kube_requests.get(rn))
        for cname, cont in list(pod_info.init_containers.items())
        + list(pod_info.running_containers.items())
    )
    memo = getattr(pod_info, "_kubetpu_prep", None)
    if memo is not None and rn in memo:
        want, has_base, old_fp = memo[rn]
        if old_fp == fp:
            return want, has_base
    for cont in list(pod_info.init_containers.values()) + list(
        pod_info.running_containers.values()
    ):
        set_device_reqs(dc, cont)
    want = pod_device_count(dc, pod_info)
    has_base = any(
        dc.any_base_re.match(k)
        for cont in list(pod_info.running_containers.values())
        + list(pod_info.init_containers.values())
        for k in cont.dev_requests
    )
    if memo is None:
        memo = {}
        pod_info._kubetpu_prep = memo  # plain dataclass: attribute is fine
    # fingerprint AFTER set_device_reqs (it mutates requests to the merge)
    fp = tuple(
        (cname, cont.requests.get(rn), cont.kube_requests.get(rn))
        for cname, cont in list(pod_info.init_containers.items())
        + list(pod_info.running_containers.items())
    )
    memo[rn] = (want, has_base, fp)
    return want, has_base


def pod_device_count(dc: DeviceClass, pod_info: PodInfo) -> int:
    """Total devices a pod needs: running containers sum, init containers
    max (reference ConvertToBestGPURequests counting, gpu.go:294-303).
    Callers run this after ``set_device_reqs``, so ``requests`` already holds
    the kube/device max-merge."""
    num = 0
    for cont in pod_info.running_containers.values():
        num += cont.requests.get(dc.resource_name, 0)
    for cont in pod_info.init_containers.values():
        num = max(num, cont.requests.get(dc.resource_name, 0))
    return int(num)


def pod_device_need(dc: DeviceClass, pod_info: PodInfo) -> int:
    """``pod_device_count`` that is safe BEFORE ``set_device_reqs``: the
    kube/device max-merge is applied inline per container (the same
    semantics the merge writes back later). For capacity pre-filters on
    un-translated pods — gang templates, queue heads."""
    num = 0
    for cont in pod_info.running_containers.values():
        num += max(
            cont.requests.get(dc.resource_name, 0),
            cont.kube_requests.get(dc.resource_name, 0),
        )
    for cont in pod_info.init_containers.values():
        num = max(
            num,
            max(
                cont.requests.get(dc.resource_name, 0),
                cont.kube_requests.get(dc.resource_name, 0),
            ),
        )
    return int(num)


def pod_wants_device(dc: DeviceClass, pod_info: PodInfo) -> bool:
    """Does the pod request any devices of this class, counting BOTH
    device-native and kube-native requests over BOTH container kinds (the
    same max-merge semantics ``set_device_reqs`` applies later) — the one
    place this question is answered (gang detection, preemption
    eligibility, perfect-score bounds)."""
    return any(
        max(
            cont.requests.get(dc.resource_name, 0),
            cont.kube_requests.get(dc.resource_name, 0),
        )
        > 0
        for cont in list(pod_info.running_containers.values())
        + list(pod_info.init_containers.values())
    )


def translate_device_resources(
    dc: DeviceClass,
    needed: int,
    node_resources: ResourceList,
    container_requests: ResourceList,
) -> ResourceList:
    """3-stage translation of a container's requests to the max level the
    node advertises (reference TranslateGPUResources, gpu.go:16-66)."""
    # Stage 1: expand scalar count into per-card keys — only when the node
    # advertises grouped cards at all (gpu.go:18-30).
    need_translation = any(dc.cards_re.search(res) for res in node_resources)
    if not need_translation:
        return container_requests

    have = 0
    max_index = -1
    for res in container_requests:
        m = dc.cards_re.search(res)
        if m:
            have += 1
            try:
                max_index = max(max_index, int(m.group(1)))
            except ValueError:
                pass
    for i in range(int(needed) - have):
        add_group_resource(container_requests, dc.base + "/" + str(max_index + i + 1) + "/cards", 1)

    # Stages 2-3: wrap one hierarchy level at a time (gpu.go:55-58).
    modified2, container_requests = translate_resource(
        node_resources, container_requests, dc.grp0, dc.base
    )
    modified3, container_requests = translate_resource(
        node_resources, container_requests, dc.grp1, dc.grp0
    )
    if modified2 or modified3:
        utils.logf(3, "New resources: %s", container_requests)
    return container_requests


def translate_device_container_resources(
    dc: DeviceClass, alloc: ResourceList, cont: ContainerInfo
) -> ResourceList:
    """Reference TranslateGPUContainerResources (gpu.go:75-78)."""
    needed = cont.requests.get(dc.resource_name, 0)
    return translate_device_resources(dc, needed, alloc, cont.dev_requests)


def set_device_reqs(dc: DeviceClass, cont: ContainerInfo) -> None:
    """Merge kube-native and device-native scalar counts via max
    (reference SetGPUReqs, gpu.go:80-92)."""
    dev = cont.requests.get(dc.resource_name)
    kube = cont.kube_requests.get(dc.resource_name)
    if dev is not None and kube is not None:
        cont.requests[dc.resource_name] = max(dev, kube)
    elif dev is not None:
        pass
    elif kube is not None:
        cont.requests[dc.resource_name] = kube
    else:
        cont.requests[dc.resource_name] = 0


def translate_pod_device_resources(
    dc: DeviceClass,
    cache: NodeTreeCache,
    node_info: NodeInfo,
    pod_info: PodInfo,
    best_tree=None,
) -> Tuple[Optional[str], bool]:
    """Per-pod orchestrator (reference TranslatePodGPUResources,
    gpu.go:94-127). Returns (error message or None, translation found).

    Auto-topology when the knob is absent or 1; flat node-shaped translation
    when 0; error otherwise. *best_tree* optionally pins the target shape
    (used by the TPU scheduler to translate against THIS node's shape rather
    than the globally-best cached shape).
    """
    for cont in pod_info.init_containers.values():
        set_device_reqs(dc, cont)
    for cont in pod_info.running_containers.values():
        set_device_reqs(dc, cont)

    req = pod_info.requests.get(dc.topology_gen_key)
    found = True
    if req is None or req == 1:  # auto-generate best topology by default
        found = convert_to_best_requests(dc, cache, pod_info, best_tree=best_tree)
        if found:
            utils.logf(4, "Auto-generated topology using best tree: %s", pod_info)
            return None, True

    if not found or req == 0:  # zero implies flat (no grouping)
        for name, cont in pod_info.init_containers.items():
            cont.dev_requests = translate_device_container_resources(
                dc, node_info.allocatable, cont
            )
        for name, cont in pod_info.running_containers.items():
            cont.dev_requests = translate_device_container_resources(
                dc, node_info.allocatable, cont
            )
        utils.logf(4, "Auto-generated topology using no topology: %s", pod_info)
        return None, True

    utils.errorf("Invalid topology generation request %s", req)
    return "invalid topology generation request", False
