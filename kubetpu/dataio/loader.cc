// kubetpu native data loader — memory-mapped token-file reader.
//
// The runtime around the TPU compute path is native where the reference's
// would be (SURVEY.md §2 note on native components; the reference itself
// ships no data loader — its only native code is the NVML probe). This is
// the input-pipeline analog of tpuinfo/gpuinfo: a small C++ component
// behind a stable C ABI, loaded from Python with ctypes (no pybind11 in
// this environment).
//
// Design: the corpus is one flat binary file of little-endian token ids
// (uint16 or uint32). The file is mmap'd — the OS page cache is the
// buffer pool, nothing is read eagerly — and batch assembly is a C-speed
// gather of [offset, offset+seq) windows into a caller-provided int32
// buffer (JAX's int32 tokens), replacing per-sequence Python slicing.
//
// C ABI (every function returns 0/NULL on failure; see errno):
//   ktpu_open(path, dtype_bytes) -> handle      dtype_bytes in {2, 4}
//   ktpu_num_tokens(handle) -> long long
//   ktpu_gather(handle, offsets, n, seq, out)   out: n*seq int32, row-major
//   ktpu_close(handle)
//
// Build: make dataio -> _output/libkubetpu_dataio.so

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Handle {
  void* base = nullptr;
  long long file_bytes = 0;
  int dtype_bytes = 0;
};

}  // namespace

extern "C" {

void* ktpu_open(const char* path, int dtype_bytes) {
  if (dtype_bytes != 2 && dtype_bytes != 4) return nullptr;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size <= 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);  // the mapping keeps the file alive
  if (base == MAP_FAILED) return nullptr;
  Handle* h = new Handle();
  h->base = base;
  h->file_bytes = st.st_size;
  h->dtype_bytes = dtype_bytes;
  return h;
}

long long ktpu_num_tokens(void* handle) {
  if (!handle) return 0;
  Handle* h = static_cast<Handle*>(handle);
  return h->file_bytes / h->dtype_bytes;
}

// Gather n windows of seq tokens each at the given token offsets into
// out (n*seq int32, row-major). Returns the number of rows written; rows
// whose window would run past the end of the file are skipped (callers
// pre-validate offsets, this is the memory-safety backstop).
int ktpu_gather(void* handle, const long long* offsets, int n, int seq,
                int32_t* out) {
  if (!handle || !offsets || !out || n <= 0 || seq <= 0) return 0;
  Handle* h = static_cast<Handle*>(handle);
  long long total = h->file_bytes / h->dtype_bytes;
  int written = 0;
  for (int i = 0; i < n; i++) {
    long long off = offsets[i];
    // no-overflow form: total >= 0 and seq > 0, so `total - seq` cannot
    // overflow, while `off + seq` would be UB for off near LLONG_MAX —
    // a compiler may elide an overflowing check, gutting the backstop
    if (off < 0 || off > total - seq) continue;
    int32_t* row = out + static_cast<long long>(written) * seq;
    if (h->dtype_bytes == 2) {
      const uint16_t* src = static_cast<const uint16_t*>(h->base) + off;
      for (int t = 0; t < seq; t++) row[t] = src[t];
    } else {
      const uint32_t* src = static_cast<const uint32_t*>(h->base) + off;
      for (int t = 0; t < seq; t++) row[t] = static_cast<int32_t>(src[t]);
    }
    written++;
  }
  return written;
}

void ktpu_close(void* handle) {
  if (!handle) return;
  Handle* h = static_cast<Handle*>(handle);
  if (h->base) munmap(h->base, h->file_bytes);
  delete h;
}

}  // extern "C"
