// tpuinfo — native TPU hardware enumerator.
//
// The TPU analog of the reference's nvmlinfo binary
// (nvidiagpuplugin/nvmlinfo/main.go): a short-lived native process that
// probes local accelerator hardware and prints one JSON object on stdout,
// isolating hardware-query code from the long-running Python node agent
// behind the same exec-JSON process boundary the reference uses
// (nvgputypes/types.go:45-58).
//
// Probe sources, in order:
//   1. /dev/accel*    — TPU device nodes on a TPU-VM (count + paths)
//   2. environment    — TPU_ACCELERATOR_TYPE (e.g. "v5litepod-8"),
//                       TPU_WORKER_ID / TPU_HOST_INDEX (host index within a
//                       multi-host slice); the libtpu runtime env contract
//   3. sysfs          — <root>/class/accel/accel<N>/ entries (a second
//                       device-discovery source, e.g. when /dev is masked),
//                       plus per-device enrichment from device/vendor,
//                       device/device and device/model where present. The
//                       root defaults to /sys and is overridable via
//                       TPUINFO_SYSFS_ROOT so tests can fixture it.
//
// Chip torus coordinates are the fixed row-major bijection from (topology,
// host index, local chip index) — the same model kubetpu's Python mesh layer
// uses — so the probe needs no libtpu RPC to emit geometry.
//
// Modes:
//   tpuinfo json                   probe hardware, print JSON
//   tpuinfo --fake v5e-8 [opts]    print a canned topology (fixture mode,
//                                  the analog of the reference's fake
//                                  plugin JSON, nvidia_gpu_manager_test.go)
//       opts: --host N     host index within the slice (default 0)
//             --missing A,B simulate failed local chips
//   tpuinfo                        human-readable device dump
//
// Wire schema (kubetpu/device/types.py parse_tpus_info):
//   {"Version":{"Runtime":...,"Libtpu":...},
//    "Topology":{"Type":...,"HostIndex":N,"NumHosts":N},
//    "Devices":[{"ID":...,"Model":...,"Path":...,"Index":N,
//                "Memory":{"Global":BYTES},"Coords":[x,y(,z)]}]}

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <dirent.h>
#include <string>
#include <vector>

#include "../native/json_escape.h"

namespace {

struct Topology {
  const char* name;        // kubetpu topology name
  const char* accel_type;  // GCE accelerator-type alias
  int mesh[3];             // mesh shape (z==0 -> 2D)
  int host[3];             // host block shape
  long long hbm_bytes;     // HBM per chip
  const char* model;
};

constexpr long long GiB = 1024LL * 1024 * 1024;

// Mirrors kubetpu/plugintypes/mesh.py TOPOLOGIES (v5e hosts own a 2x4
// block of 8 chips per SURVEY.md §7 step 2).
const Topology kTopologies[] = {
    {"v5e-1", "v5litepod-1", {1, 1, 0}, {1, 1, 0}, 16 * GiB, "TPU v5e"},
    {"v5e-4", "v5litepod-4", {2, 2, 0}, {2, 2, 0}, 16 * GiB, "TPU v5e"},
    {"v5e-8", "v5litepod-8", {2, 4, 0}, {2, 4, 0}, 16 * GiB, "TPU v5e"},
    {"v5e-16", "v5litepod-16", {4, 4, 0}, {2, 4, 0}, 16 * GiB, "TPU v5e"},
    {"v5e-32", "v5litepod-32", {4, 8, 0}, {2, 4, 0}, 16 * GiB, "TPU v5e"},
    {"v5e-64", "v5litepod-64", {8, 8, 0}, {2, 4, 0}, 16 * GiB, "TPU v5e"},
    {"v5e-128", "v5litepod-128", {8, 16, 0}, {2, 4, 0}, 16 * GiB, "TPU v5e"},
    {"v5e-256", "v5litepod-256", {16, 16, 0}, {2, 4, 0}, 16 * GiB, "TPU v5e"},
    {"v4-8", "v4-8", {2, 2, 2}, {2, 2, 1}, 32 * GiB, "TPU v4"},
    {"v4-16", "v4-16", {2, 2, 4}, {2, 2, 1}, 32 * GiB, "TPU v4"},
    {"v4-32", "v4-32", {2, 2, 8}, {2, 2, 1}, 32 * GiB, "TPU v4"},
    {"v4-64", "v4-64", {4, 4, 4}, {2, 2, 1}, 32 * GiB, "TPU v4"},
    {"v5p-8", "v5p-8", {2, 2, 2}, {2, 2, 1}, 95 * GiB, "TPU v5p"},
};

const Topology* FindTopology(const std::string& name) {
  for (const auto& t : kTopologies) {
    if (name == t.name || name == t.accel_type) return &t;
  }
  return nullptr;
}

int Dims(const Topology& t) { return t.mesh[2] == 0 ? 2 : 3; }

int ChipsPerHost(const Topology& t) {
  int n = 1;
  for (int d = 0; d < Dims(t); d++) n *= t.host[d];
  return n;
}

int NumHosts(const Topology& t) {
  int n = 1;
  for (int d = 0; d < Dims(t); d++) n *= t.mesh[d] / t.host[d];
  return n;
}

// Global coords of local chip `idx` on host `host_index`: hosts tile the
// mesh in row-major blocks; local ids are row-major within the block
// (mesh.py TpuTopology.host_coords).
void ChipCoords(const Topology& t, int host_index, int idx, int out[3]) {
  int dims = Dims(t);
  int hosts_per_dim[3], block[3], local[3];
  for (int d = 0; d < dims; d++) hosts_per_dim[d] = t.mesh[d] / t.host[d];
  for (int d = dims - 1; d >= 0; d--) {
    block[d] = host_index % hosts_per_dim[d];
    host_index /= hosts_per_dim[d];
  }
  for (int d = dims - 1; d >= 0; d--) {
    local[d] = idx % t.host[d];
    idx /= t.host[d];
  }
  for (int d = 0; d < dims; d++) out[d] = block[d] * t.host[d] + local[d];
}

struct Chip {
  std::string id;
  std::string path;
  std::string model;   // per-chip model (sysfs may override the table's)
  std::string vendor;  // PCI vendor id string from sysfs, e.g. "0x1ae0"
  std::string device;  // PCI device id string from sysfs
  int index;
  int coords[3];
  int ndims;
};

struct ProbeResult {
  const Topology* topo = nullptr;
  int host_index = 0;
  std::string slice_id = "slice0";  // physical slice identity (DCN boundary)
  std::string runtime;
  std::string libtpu;
  std::vector<Chip> chips;
};

std::string EnvOr(const char* key, const char* fallback) {
  const char* v = getenv(key);
  return v ? std::string(v) : std::string(fallback);
}

// Collect accel<N> indices from one directory of accel-named entries.
std::vector<int> ScanAccelNames(const std::string& dir_path) {
  std::vector<int> found;
  DIR* dir = opendir(dir_path.c_str());
  if (!dir) return found;
  while (dirent* ent = readdir(dir)) {
    if (strncmp(ent->d_name, "accel", 5) == 0) {
      char* end = nullptr;
      long idx = strtol(ent->d_name + 5, &end, 10);
      if (end && *end == '\0') found.push_back(static_cast<int>(idx));
    }
  }
  closedir(dir);
  return found;
}

std::string SysfsRoot() { return EnvOr("TPUINFO_SYSFS_ROOT", "/sys"); }

// First line of a sysfs attribute file, trimmed; "" when absent.
std::string ReadSysfsAttr(int idx, const char* attr) {
  char path[256];
  snprintf(path, sizeof(path), "%s/class/accel/accel%d/device/%s",
           SysfsRoot().c_str(), idx, attr);
  FILE* f = fopen(path, "r");
  if (!f) return "";
  char buf[128] = {0};
  if (!fgets(buf, sizeof(buf), f)) buf[0] = '\0';
  fclose(f);
  size_t len = strlen(buf);
  while (len > 0 && (buf[len - 1] == '\n' || buf[len - 1] == '\r' ||
                     buf[len - 1] == ' '))
    buf[--len] = '\0';
  return buf;
}

// Union of /dev/accel<N> nodes and <sysfs>/class/accel/accel<N> entries,
// sorted ascending (sysfs covers environments where /dev is masked, e.g.
// non-privileged containers; the reference's NVML probe likewise reports
// devices the runtime may not yet expose as nodes).
std::vector<int> ScanAccelDevices() {
  std::vector<int> found = ScanAccelNames("/dev");
  for (int idx : ScanAccelNames(SysfsRoot() + "/class/accel")) {
    bool seen = false;
    for (int f : found)
      if (f == idx) seen = true;
    if (!seen) found.push_back(idx);
  }
  for (size_t i = 0; i < found.size(); i++)  // insertion sort (tiny n)
    for (size_t j = i + 1; j < found.size(); j++)
      if (found[j] < found[i]) {
        int t = found[i];
        found[i] = found[j];
        found[j] = t;
      }
  return found;
}

ProbeResult ProbeHardware() {
  ProbeResult r;
  std::string accel_type = EnvOr("TPU_ACCELERATOR_TYPE", "");
  r.topo = FindTopology(accel_type);
  r.host_index = atoi(EnvOr("TPU_HOST_INDEX", EnvOr("TPU_WORKER_ID", "0").c_str()).c_str());
  r.slice_id = EnvOr("TPU_SLICE_ID", "slice0");
  r.runtime = EnvOr("TPU_RUNTIME_VERSION", "");
  r.libtpu = EnvOr("TPU_LIBRARY_VERSION", "");

  std::vector<int> devs = ScanAccelDevices();
  if (r.topo == nullptr && !devs.empty()) {
    // No accelerator-type env: infer a single-host topology from the count.
    char guess[32];
    snprintf(guess, sizeof(guess), "v5e-%zu", devs.size());
    r.topo = FindTopology(guess);
  }
  std::vector<int> dev_nodes = ScanAccelNames("/dev");
  for (int idx : devs) {
    Chip c;
    char buf[64];
    bool has_node = false;
    for (int d : dev_nodes)
      if (d == idx) has_node = true;
    if (has_node) {
      snprintf(buf, sizeof(buf), "/dev/accel%d", idx);
      c.path = buf;
    }  // sysfs-only discovery (masked /dev): no device node to inject —
       // Path stays empty and the manager skips it at allocate time
    c.index = idx;
    // sysfs enrichment (probe source 3): PCI ids always recorded when
    // present; an explicit model attribute (driver-provided) wins over the
    // topology table; the Google PCI vendor id at least brands an
    // otherwise-unidentified chip.
    c.vendor = ReadSysfsAttr(idx, "vendor");
    c.device = ReadSysfsAttr(idx, "device");
    std::string sys_model = ReadSysfsAttr(idx, "model");
    if (!sys_model.empty())
      c.model = sys_model;
    else if (r.topo)
      c.model = r.topo->model;
    else if (c.vendor == "0x1ae0")
      c.model = "Google TPU";
    else
      c.model = "TPU";
    if (r.topo) {
      snprintf(buf, sizeof(buf), "TPU-%s-h%d-c%d", r.topo->name, r.host_index, idx);
      c.id = buf;
      c.ndims = Dims(*r.topo);
      ChipCoords(*r.topo, r.host_index, idx, c.coords);
    } else {
      snprintf(buf, sizeof(buf), "TPU-unknown-c%d", idx);
      c.id = buf;
      c.ndims = 0;
    }
    r.chips.push_back(c);
  }
  return r;
}

ProbeResult FakeProbe(const std::string& topo_name, int host_index,
                      const std::string& slice_id, const std::vector<int>& missing) {
  ProbeResult r;
  r.topo = FindTopology(topo_name);
  if (!r.topo) {
    fprintf(stderr, "tpuinfo: unknown topology %s\n", topo_name.c_str());
    exit(2);
  }
  r.host_index = host_index;
  r.slice_id = slice_id;
  r.runtime = "fake";
  r.libtpu = "0.0.0-fake";
  for (int i = 0; i < ChipsPerHost(*r.topo); i++) {
    bool skip = false;
    for (int m : missing)
      if (m == i) skip = true;
    if (skip) continue;
    Chip c;
    char buf[64];
    snprintf(buf, sizeof(buf), "TPU-%s-h%d-c%d", r.topo->name, host_index, i);
    c.id = buf;
    snprintf(buf, sizeof(buf), "/dev/accel%d", i);
    c.path = buf;
    c.model = r.topo->model;
    c.index = i;
    c.ndims = Dims(*r.topo);
    ChipCoords(*r.topo, host_index, i, c.coords);
    r.chips.push_back(c);
  }
  return r;
}

using kubetpu::JsonEscape;

void PrintJson(const ProbeResult& r) {
  printf("{\"Version\":{\"Runtime\":\"%s\",\"Libtpu\":\"%s\"},",
         JsonEscape(r.runtime).c_str(), JsonEscape(r.libtpu).c_str());
  printf("\"Topology\":{\"Type\":\"%s\",\"HostIndex\":%d,\"NumHosts\":%d,\"SliceId\":\"%s\"},",
         r.topo ? r.topo->name : "", r.host_index, r.topo ? NumHosts(*r.topo) : 1,
         JsonEscape(r.slice_id).c_str());
  printf("\"Devices\":[");
  for (size_t i = 0; i < r.chips.size(); i++) {
    const Chip& c = r.chips[i];
    if (i) printf(",");
    printf("{\"ID\":\"%s\",\"Model\":\"%s\",\"Path\":\"%s\",\"Index\":%d,",
           JsonEscape(c.id).c_str(),
           c.model.empty() ? "TPU" : JsonEscape(c.model).c_str(),
           JsonEscape(c.path).c_str(), c.index);
    if (!c.vendor.empty() || !c.device.empty())
      printf("\"Pci\":{\"Vendor\":\"%s\",\"Device\":\"%s\"},",
             JsonEscape(c.vendor).c_str(), JsonEscape(c.device).c_str());
    printf("\"Memory\":{\"Global\":%lld},", r.topo ? r.topo->hbm_bytes : 0LL);
    printf("\"Coords\":[");
    for (int d = 0; d < c.ndims; d++) {
      if (d) printf(",");
      printf("%d", c.coords[d]);
    }
    printf("]}");
  }
  printf("]}\n");
}

void PrintHuman(const ProbeResult& r) {
  printf("Topology: %s host %d/%d\n", r.topo ? r.topo->name : "(unknown)", r.host_index,
         r.topo ? NumHosts(*r.topo) : 1);
  printf("Chips: %zu\n", r.chips.size());
  for (const Chip& c : r.chips) {
    printf("  [%d] %s %s coords=(", c.index, c.id.c_str(), c.path.c_str());
    for (int d = 0; d < c.ndims; d++) printf(d ? ",%d" : "%d", c.coords[d]);
    printf(")\n");
  }
}

std::vector<int> ParseIntList(const std::string& s) {
  std::vector<int> out;
  size_t pos = 0;
  while (pos < s.size()) {
    size_t comma = s.find(',', pos);
    if (comma == std::string::npos) comma = s.size();
    out.push_back(atoi(s.substr(pos, comma - pos).c_str()));
    pos = comma + 1;
  }
  return out;
}

}  // namespace

int main(int argc, char** argv) {
  bool json = false;
  bool human = false;
  std::string fake_topo;
  std::string slice_id = "slice0";
  int host_index = 0;
  std::vector<int> missing;
  for (int i = 1; i < argc; i++) {
    std::string arg = argv[i];
    if (arg == "json") {
      json = true;
    } else if (arg == "--fake" && i + 1 < argc) {
      fake_topo = argv[++i];
      json = true;
    } else if (arg == "--host" && i + 1 < argc) {
      host_index = atoi(argv[++i]);
    } else if (arg == "--slice" && i + 1 < argc) {
      slice_id = argv[++i];
    } else if (arg == "--missing" && i + 1 < argc) {
      missing = ParseIntList(argv[++i]);
    } else if (arg == "--human") {
      human = true;
    } else {
      fprintf(stderr,
              "usage: tpuinfo [json] [--fake TOPO [--host N] [--slice ID] [--missing A,B]] "
              "[--human]\n");
      return 2;
    }
  }

  ProbeResult r = fake_topo.empty()
                      ? ProbeHardware()
                      : FakeProbe(fake_topo, host_index, slice_id, missing);
  if (json && !human)
    PrintJson(r);
  else
    PrintHuman(r);
  return 0;
}
