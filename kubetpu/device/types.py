"""TPU device info JSON schema + the exec-subprocess probe client.

Analog of the reference's ``nvidiagpuplugin/gpu/nvgputypes/types.go``: a JSON
wire schema emitted by the native probe binary (``tpuinfo``, the nvmlinfo
analog) and a client that shells out to it — the same deliberate process
boundary isolating native hardware-query code from the long-running agent
(reference ``types.go:45-58`` exec's ``/usr/local/bin/nvmlinfo json``).

Schema (chip coordinates replace the NVLink P2P matrix):

    {
      "Version":  {"Runtime": "...", "Libtpu": "..."},
      "Topology": {"Type": "v5e-8", "HostIndex": 0, "NumHosts": 1},
      "Devices":  [{"ID": "...", "Model": "TPU v5e", "Path": "/dev/accel0",
                    "Index": 0, "Memory": {"Global": <bytes>},
                    "Coords": [x, y]}]
    }
"""

from __future__ import annotations

import json
import os
import subprocess
from dataclasses import dataclass, field
from typing import List, Optional, Tuple


def vchip_hbm_budget(milli: int, chip_hbm_bytes: int) -> int:
    """The HBM byte budget of a vChip share (Round-18 fractional chip
    virtualization): the chip's HBM scaled by the share, floored — the
    sum of co-located shares' budgets never exceeds the chip. Stamped
    into every fractional allocation's environment
    (``KUBETPU_VCHIP_HBM_BYTES``) so the serving layer can size its
    paged pool honestly (``PagedDecodeServer(pool_frac=...)``)."""
    from kubetpu.scheduler.meshstate import MILLI_PER_CHIP

    if not 0 < milli <= MILLI_PER_CHIP:
        raise ValueError(f"milli {milli} outside (0, {MILLI_PER_CHIP}]")
    return (int(chip_hbm_bytes) * int(milli)) // MILLI_PER_CHIP


def default_tpuinfo_path() -> str:
    """Probe binary location. Configurable (SURVEY.md §5.6 flags the
    reference's hardcoded /usr/local/bin/nvmlinfo as build debt)."""
    env = os.environ.get("KUBETPU_TPUINFO_PATH")
    if env:
        return env
    repo_local = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "_output", "tpuinfo")
    if os.path.exists(repo_local):
        return repo_local
    return "/usr/local/bin/tpuinfo"


@dataclass
class MemoryInfo:
    global_bytes: int = 0  # HBM per chip, bytes (reference Memory.Global)


@dataclass
class TpuChipInfo:
    """One TPU chip (analog of reference GpuInfo, nvgputypes/types.go:22-34).

    JSON fields: ID/Model/Path/Index/Memory/Coords. The trailing fields are
    runtime-only manager state, never serialized (reference's ``json:"-"``
    fields Found/Index/InUse/TopoDone/Name).
    """

    id: str = ""
    model: str = ""
    path: str = ""
    index: int = 0
    memory: MemoryInfo = field(default_factory=MemoryInfo)
    coords: Tuple[int, ...] = ()
    # runtime-only:
    found: bool = False
    in_use: bool = False
    name: str = ""


@dataclass
class TopologyInfo:
    type: str = ""       # slice topology name, e.g. "v5e-8"
    host_index: int = 0  # this host's index within the slice
    num_hosts: int = 1
    slice_id: str = "slice0"  # identity of the physical slice (DCN boundary)


@dataclass
class VersionInfo:
    runtime: str = ""
    libtpu: str = ""


@dataclass
class TpusInfo:
    """Analog of reference GpusInfo (nvgputypes/types.go:40-43)."""

    version: VersionInfo = field(default_factory=VersionInfo)
    topology: TopologyInfo = field(default_factory=TopologyInfo)
    tpus: List[TpuChipInfo] = field(default_factory=list)


def parse_tpus_info(data: bytes | str) -> TpusInfo:
    """Decode the tpuinfo JSON wire format."""
    obj = json.loads(data)
    version = VersionInfo(
        runtime=obj.get("Version", {}).get("Runtime", ""),
        libtpu=obj.get("Version", {}).get("Libtpu", ""),
    )
    topo = obj.get("Topology", {}) or {}
    topology = TopologyInfo(
        type=topo.get("Type", ""),
        host_index=int(topo.get("HostIndex", 0)),
        num_hosts=int(topo.get("NumHosts", 1)),
        slice_id=topo.get("SliceId", "slice0") or "slice0",
    )
    chips: List[TpuChipInfo] = []
    for dev in obj.get("Devices", []) or []:
        chips.append(
            TpuChipInfo(
                id=dev.get("ID", ""),
                model=dev.get("Model", ""),
                path=dev.get("Path", ""),
                index=int(dev.get("Index", 0)),
                memory=MemoryInfo(global_bytes=int((dev.get("Memory") or {}).get("Global", 0))),
                coords=tuple(dev.get("Coords", []) or []),
            )
        )
    return TpusInfo(version=version, topology=topology, tpus=chips)


def dump_tpus_info(info: TpusInfo) -> str:
    """Encode to the wire format (used by fakes and the pure-python probe)."""
    return json.dumps(
        {
            "Version": {"Runtime": info.version.runtime, "Libtpu": info.version.libtpu},
            "Topology": {
                "Type": info.topology.type,
                "HostIndex": info.topology.host_index,
                "NumHosts": info.topology.num_hosts,
                "SliceId": info.topology.slice_id,
            },
            "Devices": [
                {
                    "ID": c.id,
                    "Model": c.model,
                    "Path": c.path,
                    "Index": c.index,
                    "Memory": {"Global": c.memory.global_bytes},
                    "Coords": list(c.coords),
                }
                for c in info.tpus
            ],
        }
    )


def get_devices(
    tpuinfo_path: Optional[str] = None,
    timeout: float = 30.0,
    extra_args: Optional[List[str]] = None,
) -> TpusInfo:
    """Exec the native probe and parse its JSON — the process boundary of
    reference GetDevices (nvgputypes/types.go:45-58). ``extra_args`` pins
    a fixture box (e.g. ``["--fake", "v5e-8"]``) while keeping the REAL
    exec boundary — how heterogeneous wire tests run a native-probe agent
    without hardware."""
    path = tpuinfo_path or default_tpuinfo_path()
    output = subprocess.run(
        [path, "json", *(extra_args or [])],
        capture_output=True, timeout=timeout, check=True,
    ).stdout
    return parse_tpus_info(output)
