"""GPU info JSON schema (the NVML wire format of the reference,
``nvidiagpuplugin/gpu/nvgputypes/types.go:8-43``): UUID/Model/Path, HBM in
MiB, PCI bus id, and the per-device P2P ``Topology`` list of (BusID, Link)
pairs. Field names match the reference schema — it is a wire format shared
with nvidia tooling, not code."""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class MemoryInfo:
    global_mib: int = 0  # reference Memory.Global arrives in MiB over HTTP/JSON


@dataclass
class PciInfo:
    bus_id: str = ""
    bandwidth: int = 0


@dataclass
class TopologyInfo:
    bus_id: str = ""
    link: int = 0  # P2P link level 1..6 (nvidia_gpu_manager.go:158-176)


@dataclass
class GpuInfo:
    id: str = ""
    model: str = ""
    path: str = ""
    memory: MemoryInfo = field(default_factory=MemoryInfo)
    pci: PciInfo = field(default_factory=PciInfo)
    topology: List[TopologyInfo] = field(default_factory=list)
    # runtime-only (reference json:"-" fields):
    found: bool = False
    index: int = 0
    in_use: bool = False
    topo_done: bool = False
    name: str = ""


@dataclass
class VersionInfo:
    driver: str = ""
    cuda: str = ""


@dataclass
class GpusInfo:
    version: VersionInfo = field(default_factory=VersionInfo)
    gpus: List[GpuInfo] = field(default_factory=list)


def parse_gpus_info(data: bytes | str) -> GpusInfo:
    obj = json.loads(data)
    version = VersionInfo(
        driver=obj.get("Version", {}).get("Driver", ""),
        cuda=obj.get("Version", {}).get("CUDA", ""),
    )
    gpus: List[GpuInfo] = []
    for dev in obj.get("Devices", []) or []:
        topo = [
            TopologyInfo(bus_id=t.get("BusID", ""), link=int(t.get("Link", 0)))
            for t in (dev.get("Topology") or [])
        ]
        gpus.append(
            GpuInfo(
                id=dev.get("UUID", ""),
                model=dev.get("Model", ""),
                path=dev.get("Path", ""),
                memory=MemoryInfo(global_mib=int((dev.get("Memory") or {}).get("Global", 0))),
                pci=PciInfo(
                    bus_id=(dev.get("PCI") or {}).get("BusID", ""),
                    bandwidth=int((dev.get("PCI") or {}).get("Bandwidth", 0)),
                ),
                topology=topo,
            )
        )
    return GpusInfo(version=version, gpus=gpus)


def dump_gpus_info(info: GpusInfo) -> str:
    return json.dumps(
        {
            "Version": {"Driver": info.version.driver, "CUDA": info.version.cuda},
            "Devices": [
                {
                    "UUID": g.id,
                    "Model": g.model,
                    "Path": g.path,
                    "Memory": {"Global": g.memory.global_mib},
                    "PCI": {"BusID": g.pci.bus_id, "Bandwidth": g.pci.bandwidth},
                    "Topology": [
                        {"BusID": t.bus_id, "Link": t.link} for t in g.topology
                    ]
                    or None,
                }
                for g in info.gpus
            ],
        }
    )
