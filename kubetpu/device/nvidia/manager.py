"""NVIDIA GPU device manager: probe, P2P topology grouping, advertisement,
allocation. Functional mirror of the reference ``NvidiaGPUManager``
(``nvidiagpuplugin/gpu/nvidia/nvidia_gpu_manager.go``), kept for
heterogeneous GPU+TPU clusters.

Unlike the TPU manager's geometric naming, GPU grouping is *link-typed*: a
greedy pass per level where the first ungrouped GPU founds a group and
absorbs every GPU reachable over an allowed P2P link type — pass 0 with
links {6,5,4} (same-board / single-switch / multi-switch) -> ``gpugrp0``,
pass 1 with {6..1} (adds hostbridge / same-CPU / cross-CPU) -> ``gpugrp1``
(reference topologyDiscovery, ``:63-91``, link-level semantics documented at
``:158-176``).
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional, Sequence

from kubetpu.api import utils
from kubetpu.api.device import AllocateResult, Device
from kubetpu.api.types import ContainerInfo, NodeInfo, PodInfo, add_group_resource
from kubetpu.device.nvidia import types as nvtypes
from kubetpu.device.nvidia.plugin import NvidiaDockerPlugin, NvidiaFakePlugin, NvidiaPlugin
from kubetpu.plugintypes import ResourceGPU
from kubetpu.scheduler.deviceclass import GPU

_CLI_TOKEN_RE = re.compile(r"(.*?)=(.*)")


class NvidiaGPUManager(Device):
    def __init__(self, plugin: Optional[NvidiaPlugin] = None):
        self._lock = threading.Lock()
        self._plugin: NvidiaPlugin = plugin if plugin is not None else NvidiaDockerPlugin()
        self.gpus: Dict[str, nvtypes.GpuInfo] = {}
        self.path_to_id: Dict[str, str] = {}
        self.bus_id_to_id: Dict[str, str] = {}
        self.index_to_id: List[str] = []
        self.num_gpus = 0

    # -- Device lifecycle ---------------------------------------------------

    def new(self) -> None:
        with self._lock:
            self.gpus = {}

    def start(self) -> None:
        try:
            self.update_gpu_info()
        except Exception as e:  # noqa: BLE001 — degrade to zero GPUs (:185-188)
            utils.logf(0, "initial GPU probe failed (%s); starting with 0 GPUs", e)

    def get_name(self) -> str:
        return "nvidiagpu"

    # -- topology discovery (reference :63-91) ------------------------------

    def _topology_discovery(self, links: Sequence[int], level: int) -> None:
        link_set = set(links)
        for gpu in self.gpus.values():
            gpu.topo_done = False
        link_id = 0
        for gid in self.index_to_id:
            gpu = self.gpus[gid]
            if not gpu.found or gpu.topo_done:
                continue
            prefix = f"gpugrp{level}/{link_id}"
            link_id += 1
            gpu.name = prefix + "/" + gpu.name
            gpu.topo_done = True
            for topolink in gpu.topology:
                if topolink.link in link_set:
                    other_id = self.bus_id_to_id.get(topolink.bus_id)
                    if other_id is None:
                        continue
                    other = self.gpus[other_id]
                    if other.found and not other.topo_done:
                        other.name = prefix + "/" + other.name
                        other.topo_done = True

    # -- probing (reference UpdateGPUInfo, :94-183) -------------------------

    def update_gpu_info(self) -> None:
        with self._lock:
            body = self._plugin.get_gpu_info()
            utils.logf(5, "get_gpu_info returns %s", body)
            info = nvtypes.parse_gpus_info(body)
            # unit conversion: HTTP/fake backends report MiB / MB (:125-130)
            for g in info.gpus:
                g.memory.global_mib *= 1024 * 1024  # now bytes
                g.pci.bandwidth *= 1000 * 1000

            for gpu in self.gpus.values():
                gpu.found = False
            self.path_to_id = {}
            self.bus_id_to_id = {}
            self.index_to_id = [""] * len(info.gpus)
            for index, found in enumerate(info.gpus):
                prev = self.gpus.get(found.id)
                if prev is not None:
                    found.in_use = prev.in_use
                found.found = True
                found.index = index
                found.name = "gpu/" + found.id
                self.gpus[found.id] = found
                self.path_to_id[found.path] = found.id
                self.bus_id_to_id[found.pci.bus_id] = found.id
                self.index_to_id[index] = found.id
            self.num_gpus = len(info.gpus)

            self._topology_discovery([6, 5, 4], 0)
            self._topology_discovery([6, 5, 4, 3, 2, 1], 1)

    # -- advertisement (reference UpdateNodeInfo, :191-213) ------------------

    def update_node_info(self, node_info: NodeInfo) -> None:
        try:
            self.update_gpu_info()
        except Exception as e:  # noqa: BLE001
            utils.logf(0, "update_gpu_info error %s, setting GPUs to zero", e)
            # update_gpu_info released the lock when it raised
            with self._lock:
                self.num_gpus = 0
            raise
        utils.logf(4, "NumGPUs found = %d", self.num_gpus)
        # Count only found GPUs (deliberate divergence from the reference's
        # len(ngm.gpus) overcount — see tpu_manager.update_node_info).
        n = sum(1 for g in self.gpus.values() if g.found)
        for reslist in (node_info.capacity, node_info.allocatable,
                        node_info.kube_cap, node_info.kube_alloc):
            reslist[ResourceGPU] = n
        for gpu in self.gpus.values():
            if not gpu.found:
                continue
            for reslist in (node_info.capacity, node_info.allocatable):
                add_group_resource(reslist, gpu.name + "/memory", gpu.memory.global_mib)
                add_group_resource(reslist, gpu.name + "/cards", 1)

    # -- allocation ---------------------------------------------------------

    def allocate(self, pod: PodInfo, container: ContainerInfo) -> AllocateResult:
        """nvidia-docker2 path: UUIDs -> NVIDIA_VISIBLE_DEVICES env
        (reference Allocate, :216-241)."""
        with self._lock:
            if not container.allocate_from:
                return [], [], {}
            gpu_list: List[str] = []
            for res in container.allocate_from.values():
                utils.logf(4, "PodName: %s -- searching for device UID: %s", pod.name, res)
                m = GPU.alloc_re.search(res)
                if m:
                    gpu_list.append(m.group(1))
            return [], [], {"NVIDIA_VISIBLE_DEVICES": ",".join(gpu_list)}

    def allocate_old(self, pod: PodInfo, container: ContainerInfo) -> AllocateResult:
        """Legacy nvidia-docker v1 path: device paths + control devices
        parsed from the daemon's CLI string (reference AllocateOld,
        :244-304)."""
        with self._lock:
            if not container.allocate_from:
                return [], [], {}
            gpu_list: List[str] = []
            indices: List[int] = []
            for res in container.allocate_from.values():
                m = GPU.alloc_re.search(res)
                if not m:
                    continue
                gid = m.group(1)
                gpu = self.gpus.get(gid)
                if gpu is None:
                    continue
                indices.append(gpu.index)
                if gpu.found:
                    gpu_list.append(gpu.path)
            body = self._plugin.get_gpu_command_line(indices).decode()
            utils.logf(4, "PodName: %s command line from plugin: %s", pod.name, body)
            for token in body.split(" "):
                m = _CLI_TOKEN_RE.match(token)
                if m and m.group(1) == "--device":
                    val = m.group(2)
                    if val not in self.path_to_id:
                        gpu_list.append(val)  # /dev/nvidiactl, /dev/nvidia-uvm, ...
            return [], gpu_list, {}


def new_nvidia_gpu_manager() -> Device:
    """Production manager over the nvidia-docker daemon (reference
    NewNvidiaGPUManager wires the NVML path; kubetpu targets TPU-VMs, so the
    HTTP backend is the default GPU probe)."""
    mgr = NvidiaGPUManager()
    mgr.new()
    return mgr


def new_fake_nvidia_gpu_manager(
    info: nvtypes.GpusInfo, volume: str = "", volume_driver: str = ""
) -> Device:
    """Reference NewFakeNvidiaGPUManager (nvidia_fake_plugin.go:30-41)."""
    mgr = NvidiaGPUManager(plugin=NvidiaFakePlugin(info, volume, volume_driver))
    mgr.new()
    return mgr


def new_native_nvidia_gpu_manager(
    binary: str | None = None, extra_args=None
) -> Device:
    """Manager over the native gpuinfo enumerator (sysfs probe / fake box) —
    the GPU analog of the TPU manager's tpuinfo exec path, so heterogeneous
    config 5 has a native-probe story (VERDICT r1 #8)."""
    from kubetpu.device.nvidia.plugin import NvidiaNativePlugin

    mgr = NvidiaGPUManager(plugin=NvidiaNativePlugin(binary, extra_args))
    mgr.new()
    return mgr
