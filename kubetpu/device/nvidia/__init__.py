"""NVIDIA GPU device family, for heterogeneous GPU+TPU clusters (BASELINE
config 5). Functional mirror of the reference's ``nvidiagpuplugin``."""

from kubetpu.device.nvidia.manager import (
    NvidiaGPUManager,
    new_fake_nvidia_gpu_manager,
    new_native_nvidia_gpu_manager,
    new_nvidia_gpu_manager,
)
from kubetpu.device.nvidia.types import GpuInfo, GpusInfo, parse_gpus_info

__all__ = [
    "NvidiaGPUManager",
    "new_fake_nvidia_gpu_manager",
    "new_native_nvidia_gpu_manager",
    "new_nvidia_gpu_manager",
    "GpuInfo",
    "GpusInfo",
    "parse_gpus_info",
]
