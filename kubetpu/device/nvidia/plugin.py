"""GPU probe backends: the injectable ``NvidiaPlugin`` interface
(reference ``nvidia_plugin.go:7-10``), the legacy nvidia-docker v1 HTTP
daemon client (``nvidia_docker_plugin.go``), and the fake test backend
(``nvidia_fake_plugin.go``)."""

from __future__ import annotations

import os
import urllib.request
from abc import ABC, abstractmethod
from typing import List

from kubetpu.device.nvidia import types as nvtypes


def _docker_cli_fragment(paths: List[str], volume: str = "",
                         volume_driver: str = "") -> bytes:
    """The legacy nvidia-docker CLI fragment: control devices + per-GPU
    --device flags (one synthesis shared by every daemon-less backend)."""
    cli = ""
    if volume or volume_driver:
        cli = f"--volume-driver={volume_driver} --volume={volume} "
    cli += "--device=/dev/nvidiactl --device=/dev/nvidia-uvm --device=/dev/nvidia-uvm-tools"
    for path in paths:
        cli += " --device=" + path
    return cli.encode()


class NvidiaPlugin(ABC):
    @abstractmethod
    def get_gpu_info(self) -> bytes: ...

    @abstractmethod
    def get_gpu_command_line(self, device_indices: List[int]) -> bytes:
        """The legacy docker CLI fragment naming --device flags
        (reference GetGPUCommandLine)."""


class NvidiaDockerPlugin(NvidiaPlugin):
    """Client of the nvidia-docker v1 daemon REST API (reference
    nvidia_docker_plugin.go:21-27). Base URL configurable (the reference
    hardcodes localhost:3476 — SURVEY.md §5.6)."""

    def __init__(self, base_url: str | None = None):
        self.base_url = base_url or os.environ.get(
            "KUBETPU_NVIDIA_DOCKER_URL", "http://localhost:3476"
        )

    def _get(self, path: str) -> bytes:
        # Read-only GET against the LOCAL nvidia-docker daemon — a foreign
        # REST protocol, not the kubetpu wire: no trace headers to
        # propagate, no idempotency contract, and chaos fault injection
        # targets our own control plane, not the vendor daemon. The shared
        # client would add nothing but a decode round-trip.
        # ktlint: disable=KTP002
        with urllib.request.urlopen(self.base_url + path, timeout=10) as resp:
            return resp.read()

    def get_gpu_info(self) -> bytes:
        return self._get("/v1.0/gpu/info/json")

    def get_gpu_command_line(self, device_indices: List[int]) -> bytes:
        dev = "+".join(str(i) for i in device_indices)
        return self._get("/v1.0/docker/cli?dev=" + dev)


class NvidiaFakePlugin(NvidiaPlugin):
    """Canned GpusInfo + synthesized docker CLI string (reference
    nvidia_fake_plugin.go:10-28) — the key to testing without hardware."""

    def __init__(self, info: nvtypes.GpusInfo, volume: str = "", volume_driver: str = ""):
        self._info = info
        self._volume = volume
        self._volume_driver = volume_driver

    def get_gpu_info(self) -> bytes:
        return nvtypes.dump_gpus_info(self._info).encode()

    def get_gpu_command_line(self, device_indices: List[int]) -> bytes:
        return _docker_cli_fragment(
            [self._info.gpus[idx].path for idx in device_indices],
            self._volume, self._volume_driver,
        )


class NvidiaNativePlugin(NvidiaPlugin):
    """Exec the native ``gpuinfo`` enumerator — the reference's nvmlinfo
    exec-JSON process boundary (``nvgputypes/types.go:45-58``), NVML-free:
    gpuinfo reads sysfs PCI state (see ``kubetpu/gpuinfo/gpuinfo.cc``).
    Binary path from ``KUBETPU_GPUINFO_PATH``, default ``_output/gpuinfo``.
    ``extra_args`` lets callers pin a fake box (e.g. ``["--fake",
    "titan8"]``) while still crossing the real exec boundary."""

    def __init__(self, binary: str | None = None, extra_args: List[str] | None = None,
                 timeout: float = 30.0):
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        self.binary = binary or os.environ.get(
            "KUBETPU_GPUINFO_PATH", os.path.join(repo, "_output", "gpuinfo")
        )
        self.extra_args = list(extra_args or [])
        self.timeout = timeout
        self._last_info: bytes | None = None

    def get_gpu_info(self) -> bytes:
        import subprocess

        out = subprocess.run(
            [self.binary, "json", *self.extra_args],
            capture_output=True, timeout=self.timeout, check=True,
        )
        self._last_info = out.stdout
        return out.stdout

    def get_gpu_command_line(self, device_indices: List[int]) -> bytes:
        # No nvidia-docker daemon behind the native probe: synthesize the
        # legacy CLI fragment from the last probe (static hardware — don't
        # fork a fresh sysfs walk per container allocation).
        info = nvtypes.parse_gpus_info(self._last_info or self.get_gpu_info())
        return _docker_cli_fragment(
            [info.gpus[idx].path for idx in device_indices]
        )
