"""TPU probe-backend interface + fake backend.

Mirrors the reference's injectable backend pattern (``NvidiaPlugin``
interface, ``nvidia_plugin.go:7-10``; ``NvidiaFakePlugin``,
``nvidia_fake_plugin.go``): the manager's hardware probe is an interface, so
the full node-agent logic is testable with canned topologies and no
hardware — the fixture strategy SURVEY.md §4 names as the pattern to
replicate (BASELINE config 1's "fake-device mode").
"""

from __future__ import annotations

from abc import ABC, abstractmethod

from kubetpu.device import types as tputypes
from kubetpu.plugintypes.mesh import TOPOLOGIES


class TpuPlugin(ABC):
    """Backend serving raw tpuinfo JSON (analog of NvidiaPlugin.GetGPUInfo)."""

    @abstractmethod
    def get_tpu_info(self) -> bytes: ...


class FakeTpuPlugin(TpuPlugin):
    """Serves a canned TpusInfo (analog of NvidiaFakePlugin)."""

    def __init__(self, info: tputypes.TpusInfo):
        self._info = info

    def get_tpu_info(self) -> bytes:
        return tputypes.dump_tpus_info(self._info).encode()


def make_fake_tpus_info(
    topology_name: str = "v5e-8",
    host_index: int = 0,
    missing_chips: tuple = (),
    slice_uid: str = "slice0",
) -> tputypes.TpusInfo:
    """Build a realistic canned host: one chip per local index of the host's
    block, /dev/accel<i> paths, per-generation HBM — the TPU analog of the
    reference's TITAN X / K80 JSON fixtures
    (nvidia_gpu_manager_test.go:16-17). ``missing_chips`` simulates failed
    or absent devices (fault injection, SURVEY.md §5.3)."""
    topo = TOPOLOGIES[topology_name]
    host_coords = topo.host_coords(host_index)
    chips = []
    for local, coord in enumerate(host_coords):
        if local in missing_chips:
            continue
        chips.append(
            tputypes.TpuChipInfo(
                id=f"TPU-{topology_name}-h{host_index}-c{local}",
                model=f"TPU {topo.generation}",
                path=f"/dev/accel{local}",
                index=local,
                memory=tputypes.MemoryInfo(global_bytes=topo.hbm_bytes_per_chip),
                coords=coord,
            )
        )
    return tputypes.TpusInfo(
        version=tputypes.VersionInfo(runtime="fake", libtpu="0.0.0-fake"),
        topology=tputypes.TopologyInfo(
            type=topology_name, host_index=host_index, num_hosts=topo.num_hosts,
            slice_id=slice_uid,
        ),
        tpus=chips,
    )
