"""TPU device manager: probe, topology naming, advertisement, allocation.

The node-agent ``Device`` implementation — analog of the reference's
``NvidiaGPUManager`` (``nvidiagpuplugin/gpu/nvidia/nvidia_gpu_manager.go``):

- probe via the native ``tpuinfo`` subprocess with a 5-minute cache
  (reference ``:110-121``) or an injected fake backend;
- mark-and-reassign discovery that preserves ``in_use`` across refreshes and
  tolerates disappearing chips (reference ``:132-155``);
- topology naming: where the reference greedily groups GPUs by NVLink P2P
  link level (``:63-91``), TPU chips are named *geometrically* from their
  torus coordinates — ``tpugrp1/<host>/tpugrp0/<2x2-block>/tpu/<idx>`` —
  because ICI locality is a coordinate property, not a link-type property;
- ``update_node_info`` advertises the scalar resource, per-chip grouped
  cards/memory keys, and the ``tpu-slice`` geometry key (reference
  ``:191-213``);
- ``allocate`` turns AllocateFrom into ``/dev/accel*`` device nodes plus the
  libtpu environment contract (``TPU_VISIBLE_DEVICES``, chip-bounds and
  process-bounds variables) instead of ``NVIDIA_VISIBLE_DEVICES``
  (reference ``:216-241``; SURVEY.md §5.8).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from kubetpu.api import utils
from kubetpu.api.device import AllocateResult, Device, Mount
from kubetpu.api.types import ContainerInfo, NodeInfo, PodInfo, add_group_resource
from kubetpu.device import types as tputypes
from kubetpu.device.tpu_plugin import TpuPlugin
from kubetpu.plugintypes import ResourceTPU
from kubetpu.plugintypes.mesh import TOPOLOGIES, TpuTopology
from kubetpu.scheduler.deviceclass import TPU
from kubetpu.scheduler.meshstate import (
    MILLI_PER_CHIP,
    GangSliceIdKey,
    GangSlicesKey,
    pod_milli,
    slice_resource_key,
)

# Probe refresh period (reference nvmlLastGetTime 5-minute cache, :110-121).
PROBE_CACHE_SECONDS = 5 * 60.0


def local_block_index(topo: TpuTopology, host_index: int, coord: Tuple[int, ...]) -> int:
    """The level-0 group of a chip: aligned 2-per-dimension sub-blocks of
    the host's block (a v5e 2x4 host has two 2x2 blocks). Geometric analog
    of the reference's pass-0 link grouping (nvidia_gpu_manager.go:178)."""
    host_origin = topo.host_coords(host_index)[0]
    blocks_per_dim = [(h + 1) // 2 for h in topo.host_shape]
    idx = 0
    for c, o, n in zip(coord, host_origin, blocks_per_dim):
        idx = idx * n + min((c - o) // 2, n - 1)
    return idx


class TpuDevManager(Device):
    """Manages the local TPU chips (analog of NvidiaGPUManager)."""

    def __init__(
        self,
        plugin: Optional[TpuPlugin] = None,
        tpuinfo_path: Optional[str] = None,
        tpuinfo_args: Optional[List[str]] = None,
    ):
        self._lock = threading.Lock()
        self._plugin = plugin          # None => exec the native probe
        self._tpuinfo_path = tpuinfo_path
        self._tpuinfo_args = list(tpuinfo_args or [])
        self.tpus: Dict[str, tputypes.TpuChipInfo] = {}
        self.path_to_id: Dict[str, str] = {}
        self.index_to_id: Dict[int, str] = {}
        self.num_tpus = 0
        self.topology: Optional[TpuTopology] = None
        self.host_index = 0
        self.topology_name = ""
        self.slice_uid = "slice0"
        self._info: Optional[tputypes.TpusInfo] = None
        self._last_probe_time = 0.0

    # -- Device lifecycle ---------------------------------------------------

    def new(self) -> None:
        with self._lock:
            self.tpus = {}

    def start(self) -> None:
        """Probe errors are deliberately swallowed: the node degrades to zero
        chips (reference Start, nvidia_gpu_manager.go:185-188)."""
        try:
            self.update_tpu_info()
        except Exception as e:  # noqa: BLE001 — graceful-degradation contract
            utils.logf(0, "initial TPU probe failed (%s); starting with 0 chips", e)

    def get_name(self) -> str:
        return "tpu"

    # -- probing ------------------------------------------------------------

    def _fetch(self) -> tputypes.TpusInfo:
        if self._plugin is not None:
            return tputypes.parse_tpus_info(self._plugin.get_tpu_info())
        now = time.monotonic()
        if self._info is None or (now - self._last_probe_time) > PROBE_CACHE_SECONDS:
            self._info = tputypes.get_devices(
                self._tpuinfo_path, extra_args=self._tpuinfo_args
            )
            self._last_probe_time = now
        return self._info

    def update_tpu_info(self) -> None:
        """Refresh chip state: mark-and-reassign preserving in_use, then
        geometric topology naming (reference UpdateGPUInfo, :94-183)."""
        with self._lock:
            info = self._fetch()
            utils.logf(5, "TPUInfo: %s", info)

            self.topology = TOPOLOGIES.get(info.topology.type)
            self.topology_name = info.topology.type
            self.host_index = info.topology.host_index
            self.slice_uid = info.topology.slice_id

            # mark-and-sweep: if num_tpus != len(tpus) afterwards, some chips
            # have gone missing (reference comment at :152-154).
            for chip in self.tpus.values():
                chip.found = False
            self.path_to_id = {}
            self.index_to_id = {}
            for chip_found in info.tpus:
                prev = self.tpus.get(chip_found.id)
                if prev is not None:
                    chip_found.in_use = prev.in_use
                chip_found.found = True
                chip_found.name = self._topology_name_for(chip_found)
                self.tpus[chip_found.id] = chip_found
                self.path_to_id[chip_found.path] = chip_found.id
                self.index_to_id[chip_found.index] = chip_found.id
            self.num_tpus = len(info.tpus)

    def _topology_name_for(self, chip: tputypes.TpuChipInfo) -> str:
        """``tpugrp1/<host>/tpugrp0/<block>/tpu/<index>`` from coordinates;
        chips without geometry degrade to per-chip degenerate groups (the
        reference's topology-less K80 behavior)."""
        if self.topology is not None and chip.coords:
            blk = local_block_index(self.topology, self.host_index, chip.coords)
            return f"tpugrp1/{self.host_index}/tpugrp0/{blk}/tpu/{chip.index}"
        return f"tpugrp1/{chip.index}/tpugrp0/{chip.index}/tpu/{chip.index}"

    # -- advertisement ------------------------------------------------------

    def update_node_info(self, node_info: NodeInfo) -> None:
        """Advertise scalar + grouped + geometry resources (reference
        UpdateNodeInfo, :191-213)."""
        try:
            self.update_tpu_info()
        except Exception as e:  # noqa: BLE001
            utils.logf(0, "update_tpu_info error %s, setting TPUs to zero", e)
            # update_tpu_info released the lock when it raised
            with self._lock:
                self.num_tpus = 0
            raise
        utils.logf(4, "NumTPUs found = %d", self.num_tpus)
        # Count only currently-found chips: the map retains disappeared chips
        # (found=False) and advertising them as scalar capacity would admit
        # pods the fill step cannot satisfy. (The reference counts
        # len(ngm.gpus) here, nvidia_gpu_manager.go:199 — a latent
        # overcount; kubetpu deliberately diverges.)
        n = sum(1 for c in self.tpus.values() if c.found)
        for reslist in (node_info.capacity, node_info.allocatable,
                        node_info.kube_cap, node_info.kube_alloc):
            reslist[ResourceTPU] = n
        for chip in self.tpus.values():
            if not chip.found:
                continue
            for reslist in (node_info.capacity, node_info.allocatable):
                add_group_resource(reslist, chip.name + "/cards", 1)
                add_group_resource(reslist, chip.name + "/memory", chip.memory.global_bytes)
                # Round-18 vChips: the chip's fractional capacity in
                # milli-chips, next to the exclusive cards key — the
                # hierarchical fractional resource the scheduler
                # bin-packs small replicas onto
                add_group_resource(reslist, chip.name + "/milli", MILLI_PER_CHIP)
        if self.topology is not None:
            for reslist in (node_info.capacity, node_info.allocatable):
                reslist[
                    slice_resource_key(self.topology_name, self.host_index, self.slice_uid)
                ] = 1

    # -- allocation ---------------------------------------------------------

    def allocate(self, pod: PodInfo, container: ContainerInfo) -> AllocateResult:
        """AllocateFrom -> device nodes + libtpu env (reference Allocate,
        :216-241, which emits NVIDIA_VISIBLE_DEVICES)."""
        with self._lock:
            if not container.allocate_from:
                return [], [], {}
            indices: List[int] = []
            devices: List[str] = []
            vchip_idx: List[int] = []  # Round-18: fractionally-shared chips
            for res in container.allocate_from.values():
                utils.logf(4, "PodName: %s -- searching for device: %s", pod.name, res)
                m = TPU.alloc_re.search(res)
                if not m:
                    m = TPU.milli_alloc_re.search(res)
                    if not m:
                        continue
                    vchip_idx.append(int(m.group(1)))
                idx = int(m.group(1))
                indices.append(idx)
                chip_id = self.index_to_id.get(idx)
                if chip_id is not None and self.tpus[chip_id].found:
                    path = self.tpus[chip_id].path
                    if path:  # sysfs-only chips (masked /dev) have no node
                        devices.append(path)
            indices.sort()
            devices.sort()
            env = {
                "TPU_VISIBLE_DEVICES": ",".join(str(i) for i in indices),
                "TPU_SKIP_MDS_QUERY": "true",
                "TPU_WORKER_ID": str(self.host_index),
            }
            env.update(self._bounds_env(indices))
            # Fractional (vChip) allocation: stamp the share and its HBM
            # budget so the container's serving stack can partition the
            # paged pool honestly (pool_frac = MILLI/1000); the chip's
            # device node is shared with the co-located tenants.
            if vchip_idx:
                milli = pod_milli(pod)
                env["KUBETPU_VCHIP_MILLI"] = str(milli)
                hbm = 0
                chip_id = self.index_to_id.get(vchip_idx[0])
                if chip_id is not None:
                    hbm = self.tpus[chip_id].memory.global_bytes
                env["KUBETPU_VCHIP_HBM_BYTES"] = str(
                    tputypes.vchip_hbm_budget(milli, hbm) if milli and hbm
                    else 0)
            # Multislice gang members (stamped by schedule_gang's multislice
            # path) get the libtpu/megascale identity: how many slices the
            # job spans and which one this pod's chips live in. The
            # coordinator address is a launch-layer concern (jobs.launch
            # wires jax.distributed), not a per-chip allocation fact.
            if GangSlicesKey in pod.requests:
                env["MEGASCALE_NUM_SLICES"] = str(pod.requests[GangSlicesKey])
                env["MEGASCALE_SLICE_ID"] = str(
                    pod.requests.get(GangSliceIdKey, 0)
                )
            return [], devices, env

    def _bounds_env(self, indices: List[int]) -> Dict[str, str]:
        """Chip-bounds variables for sub-host slices: the bounding box of the
        allocated chips' coordinates, padded to 3 dims (the libtpu
        TPU_CHIPS_PER_PROCESS_BOUNDS / TPU_PROCESS_BOUNDS contract)."""
        if self.topology is None or not indices:
            return {}
        coords = []
        for idx in indices:
            chip_id = self.index_to_id.get(idx)
            if chip_id is not None and self.tpus[chip_id].coords:
                coords.append(self.tpus[chip_id].coords)
        if not coords:
            return {}
        ndims = len(coords[0])
        extent = [
            max(c[d] for c in coords) - min(c[d] for c in coords) + 1
            for d in range(ndims)
        ]
        while len(extent) < 3:
            extent.append(1)
        return {
            "TPU_CHIPS_PER_PROCESS_BOUNDS": ",".join(str(e) for e in extent),
            "TPU_PROCESS_BOUNDS": "1,1,1",
        }


def new_tpu_dev_manager(extra_args: Optional[List[str]] = None) -> Device:
    """Production manager: probes via the native tpuinfo binary (analog of
    NewNvidiaGPUManager, :35-38). ``extra_args`` pins a fixture box (e.g.
    ``["--fake", "v5e-8"]``) while keeping the real exec boundary."""
    mgr = TpuDevManager(tpuinfo_args=extra_args)
    mgr.new()
    return mgr


def new_fake_tpu_dev_manager(info: tputypes.TpusInfo) -> Device:
    """Test/fake-device manager (analog of NewFakeNvidiaGPUManager,
    nvidia_fake_plugin.go:30-41)."""
    from kubetpu.device.tpu_plugin import FakeTpuPlugin

    mgr = TpuDevManager(plugin=FakeTpuPlugin(info))
    mgr.new()
    return mgr
