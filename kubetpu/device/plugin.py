"""Device plugin entry shim (analog of reference
``nvidiagpuplugin/plugin/nvidiagpu.go:8-10``): the factory symbol the core
looks up via ``kubetpu.api.device.create_device_from_plugin``."""

from __future__ import annotations

from kubetpu.api.device import Device
from kubetpu.device.tpu_manager import new_tpu_dev_manager


def create_device_plugin() -> Device:
    return new_tpu_dev_manager()
