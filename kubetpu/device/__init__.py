"""Node-agent device managers (analog of reference ``nvidiagpuplugin``):
the TPU manager (probe via native ``tpuinfo``, geometric ICI naming,
``/dev/accel*`` + libtpu env injection) and the NVIDIA manager
(``kubetpu.device.nvidia``) for heterogeneous clusters."""

from kubetpu.device.tpu_manager import (
    TpuDevManager,
    new_fake_tpu_dev_manager,
    new_tpu_dev_manager,
)
from kubetpu.device.tpu_plugin import FakeTpuPlugin, TpuPlugin, make_fake_tpus_info

__all__ = [
    "TpuDevManager",
    "new_fake_tpu_dev_manager",
    "new_tpu_dev_manager",
    "FakeTpuPlugin",
    "TpuPlugin",
    "make_fake_tpus_info",
]
