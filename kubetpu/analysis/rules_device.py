"""Device-path rules: the static twins of the runtime pins the serving
PRs left behind.

KTP001 (hot-path-sync) is the heart: PR 5/6 proved that steady-state
``step()`` issues zero host uploads and zero device syncs by
monkeypatching ``jnp.asarray`` / ``block_until_ready`` and counting —
but that pin only fires when a test drives the exact path. Here we
flatten the serving class hierarchy (``SlotServerBase`` ->
``DecodeServer``/``PagedDecodeServer`` -> the speculative servers),
compute every method reachable from ``step()`` via ``self.*`` calls,
and flag sync/upload constructs inside that closure at the line that
introduces them.

Reachability is deliberately conservative in BOTH directions:

- it only follows ``self.method(...)`` / ``super().method(...)`` /
  same-module bare calls — a callable stored on an attribute (the jitted
  legs in ``self._step_fn``) is compiled device code and cannot host-sync
  mid-trace, so not following it is correct, not a gap;
- it stops at BARRIER methods: legs that are *architecturally allowed*
  to touch the host — admission (uploads happen at the dev-cache
  invalidation points, by design), the one materialize/route sync, and
  warmup. Everything else reachable from ``step()`` must stay clean;
  surgical exceptions (the profiler's sampled-step sync) carry inline
  ``# ktlint: disable=KTP001`` with a justification.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from kubetpu.analysis.core import (
    Finding,
    Project,
    Rule,
    SourceFile,
    call_name,
    dotted_name,
    iter_calls,
)

# the serving hot modules: where step()/round loops live. speculative.py
# and sampling.py only contribute jitted device code (called inside the
# legs), so they cannot host-sync mid-step and are not closure members.
HOT_MODULES = (
    "kubetpu/jobs/serving.py",
    "kubetpu/jobs/paged.py",
    "kubetpu/jobs/spec_serving.py",
)

# traversal roots: the per-step entry points
HOT_ROOTS = ("step",)

# legs allowed to touch the host, by architecture (module docstrings in
# serving.py spell each out): admission + prefill scheduling upload at
# the invalidation points, route/materialize IS the one designed sync,
# warmup runs before serving, retirement publishes pages by ownership
# donation (and its obs writes are host-only state). The Round-16
# migration legs (snapshot/restore and their freeze/finish bookkeeping)
# are barrier legs too: a slot handoff's device gather and page upload
# are its DESIGNED sync/transfer — they run on the wire thread between
# steps, never inside one, and anything that ever reaches them from a
# step closure must stop the traversal here, not charge the step. The
# Round-17 disaggregated-handoff legs (the mid-prefill page-span gather
# and the progress probe the handoff streamer polls) carry the same
# argument: their device gathers run on the handoff loop thread between
# steps, by design.
HOT_BARRIERS = {
    "_schedule_prefills",
    "_drain_queue_into_slots",
    "_route_step",
    "_materialize_pending",
    "warmup",
    "_warmup_buckets",
    "retire",
    "_retire_if_done",
    "enqueue",
    "cancel",
    "drain",
    "snapshot_slot",
    "restore_slot",
    "_snapshot_request",
    "_restore_request",
    "freeze_slot",
    "unfreeze_slot",
    "finish_migrated",
    "cancel_expired",
    "migratable_rids",
    "snapshot_pages",
    "_gather_page_span",
    "prefill_progress",
    # Round-19 tiered KV cache: spill (device->host gather of evicted
    # tree pages), fill (host->device upload on a host-tier match), and
    # the peer import/export legs are all barrier legs — they run at
    # admission / eviction / on the wire thread, never inside a steady-
    # state step, and the gather/upload IS each leg's designed transfer.
    "_tree_reclaim",
    "_gather_phys_pages",
    "_fill_host_prefix",
    "_fill_host_node",
    "_upload_host_pages",
    "export_prefix_span",
    "inject_prefix",
    # Round-22 multi-LoRA: adapter hot-load (one host->device factor
    # upload into the packed stack) and evict (directory bookkeeping)
    # are barrier legs — they run on the wire thread between steps,
    # never inside one; the per-step adapter-id upload rides the _dev
    # cache at the admission invalidation points instead.
    "load_adapter",
    "evict_adapter",
}

# host-sync / host-upload constructs (the same set the PR 5/6 runtime
# pins count, minus float()-on-array which is untypable statically)
_SYNC_DOTTED = {
    "jax.block_until_ready",
    "jax.device_get",
    "jax.device_put",
    "jnp.asarray",
    "np.asarray",
    "numpy.asarray",
}
_SYNC_METHODS = {"block_until_ready", "item", "tolist"}


class _ClassInfo:
    def __init__(self, name: str, path: str, node: ast.ClassDef) -> None:
        self.name = name
        self.path = path
        self.node = node
        self.bases: List[str] = [
            b for b in (dotted_name(x) for x in node.bases) if b
        ]
        self.methods: Dict[str, ast.FunctionDef] = {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item


def _collect_classes(project: Project) -> Dict[str, _ClassInfo]:
    """name -> class info across the hot modules. Names are unique there
    today; last-write-wins would only matter for a duplicate class name,
    which the serving modules do not have."""
    out: Dict[str, _ClassInfo] = {}
    for path in HOT_MODULES:
        sf = project.get(path)
        if sf is None:
            continue
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                out[node.name] = _ClassInfo(node.name, path, node)
    return out


def _module_functions(sf: SourceFile) -> Dict[str, ast.FunctionDef]:
    return {
        n.name: n for n in sf.tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _resolve_method(
    classes: Dict[str, _ClassInfo], cls: str, method: str
) -> Optional[Tuple[str, ast.FunctionDef]]:
    """(path, node) for *method* resolved through *cls*'s hierarchy
    (depth-first over base names known to the hot modules)."""
    seen: Set[str] = set()
    stack = [cls]
    while stack:
        name = stack.pop(0)
        if name in seen:
            continue
        seen.add(name)
        info = classes.get(name)
        if info is None:
            continue
        if method in info.methods:
            return info.path, info.methods[method]
        stack.extend(info.bases)
    return None


def hot_closure(project: Project) -> Dict[Tuple[str, int], Tuple[str, str, ast.FunctionDef]]:
    """Every function reachable from a hot root, keyed by
    (path, lineno) -> (path, qualified name, node). Traverses per
    concrete class so inherited methods resolve against the class that
    actually serves."""
    classes = _collect_classes(project)
    mod_funcs = {
        path: _module_functions(project.get(path))
        for path in HOT_MODULES if project.get(path) is not None
    }
    out: Dict[Tuple[str, int], Tuple[str, str, ast.FunctionDef]] = {}
    for cls_name, info in classes.items():
        root = _resolve_method(classes, cls_name, HOT_ROOTS[0])
        if root is None:
            continue
        # BFS over self./super()./bare calls from this class's step
        queue: List[Tuple[str, str, ast.FunctionDef]] = []
        visited: Set[Tuple[str, int]] = set()
        for r in HOT_ROOTS:
            hit = _resolve_method(classes, cls_name, r)
            if hit is not None:
                queue.append((hit[0], f"{cls_name}.{r}", hit[1]))
        while queue:
            path, qual, node = queue.pop(0)
            key = (path, node.lineno)
            if key in visited:
                continue
            visited.add(key)
            out.setdefault(key, (path, qual, node))
            for call in iter_calls(node):
                callee = _callee_method(call)
                if callee is not None:
                    if callee in HOT_BARRIERS:
                        continue
                    hit = _resolve_method(classes, cls_name, callee)
                    if hit is not None:
                        queue.append((hit[0], f"{cls_name}.{callee}", hit[1]))
                    continue
                bare = call_name(call)
                if bare and "." not in bare and bare not in HOT_BARRIERS:
                    fn = mod_funcs.get(path, {}).get(bare)
                    if fn is not None:
                        queue.append((path, f"{path}:{bare}", fn))
    return out


def _callee_method(call: ast.Call) -> Optional[str]:
    """Method name for ``self.X(...)`` / ``super().X(...)`` calls."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    v = f.value
    if isinstance(v, ast.Name) and v.id == "self":
        return f.attr
    if (isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
            and v.func.id == "super"):
        return f.attr
    return None


class HotPathSyncRule(Rule):
    code = "KTP001"
    name = "hot-path-sync"
    description = (
        "no host syncs/uploads (jnp.asarray, np.asarray, "
        ".block_until_ready(), .item(), .tolist(), jax.device_get/put) "
        "in functions reachable from serving step() — the static twin "
        "of the PR 5/6 zero-upload pins"
    )

    def check(self, project: Project) -> Iterable[Finding]:
        emitted: Set[Tuple[str, int, int]] = set()
        for (path, _), (_, qual, node) in sorted(hot_closure(project).items()):
            for call in iter_calls(node):
                label = self._sync_label(call)
                if label is None:
                    continue
                key = (path, call.lineno, call.col_offset)
                if key in emitted:
                    continue
                emitted.add(key)
                yield Finding(
                    path=path, line=call.lineno, col=call.col_offset,
                    code=self.code,
                    message=(
                        f"host sync/upload `{label}` in `{qual.split('.')[-1]}`"
                        f" (reachable from step() via {qual})"
                    ),
                )

    @staticmethod
    def _sync_label(call: ast.Call) -> Optional[str]:
        d = call_name(call)
        if d in _SYNC_DOTTED:
            return d
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS:
            # method-style sync on an expression: x.item(), arr.tolist(),
            # handle.block_until_ready(). A direct `self.item(...)` would
            # be a server METHOD, not an array sync — but `self._x.item()`
            # (stored array) is one, so only bare `self` is exempt.
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                return None
            return f".{f.attr}()"
        return None


class DeterminismRule(Rule):
    code = "KTP005"
    name = "determinism"
    description = (
        "no wall-clock (time.time/time_ns) or stdlib random in "
        "device-path jobs/ modules — serving sampling is "
        "request-deterministic (fold_in(seed, rid, pos)); timing shims "
        "use monotonic/perf_counter"
    )

    _JOBS_PREFIX = "kubetpu/jobs/"
    _CLOCK = {"time.time", "time.time_ns"}

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project:
            if not sf.path.startswith(self._JOBS_PREFIX):
                continue
            random_aliases = self._stdlib_random_aliases(sf.tree)
            for call in iter_calls(sf.tree):
                d = call_name(call)
                if d in self._CLOCK:
                    yield Finding(
                        path=sf.path, line=call.lineno,
                        col=call.col_offset, code=self.code,
                        message=(
                            f"wall-clock `{d}()` in a device-path module "
                            "(use time.monotonic/perf_counter for "
                            "intervals; wall time belongs to obs)"
                        ),
                    )
                elif d and "." in d and d.split(".")[0] in random_aliases:
                    yield Finding(
                        path=sf.path, line=call.lineno,
                        col=call.col_offset, code=self.code,
                        message=(
                            f"stdlib `{d}()` in a device-path module — "
                            "randomness must flow from seeded keys "
                            "(jax.random.fold_in) or seeded np.random "
                            "generators"
                        ),
                    )

    @staticmethod
    def _stdlib_random_aliases(tree: ast.Module) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "random":
                        out.add(alias.asname or "random")
        return out


class JitLegRule(Rule):
    code = "KTP006"
    name = "jit-leg-hygiene"
    description = (
        "jax.jit/jax.pmap legs must be built once and cached (leg "
        "factories at init/warmup), never constructed inside a loop or "
        "in the step() closure — a per-call jit recompiles every call"
    )

    _JIT = {"jax.jit", "jax.pmap"}

    def check(self, project: Project) -> Iterable[Finding]:
        closure_lines: Dict[str, Set[Tuple[int, int]]] = {}
        for (path, _), (_, _, node) in hot_closure(project).items():
            span = closure_lines.setdefault(path, set())
            span.add((node.lineno, getattr(node, "end_lineno", node.lineno)))
        for sf in project:
            if not sf.path.startswith("kubetpu/"):
                continue
            for call, in_loop in self._calls_with_loop_flag(sf.tree):
                if not self._is_jit_construction(call):
                    continue
                if in_loop:
                    yield Finding(
                        path=sf.path, line=call.lineno,
                        col=call.col_offset, code=self.code,
                        message=(
                            "jax.jit constructed inside a loop — each "
                            "iteration builds a fresh leg; hoist and "
                            "cache it (see the shared-leg cache)"
                        ),
                    )
                elif any(lo <= call.lineno <= hi
                         for lo, hi in closure_lines.get(sf.path, ())):
                    yield Finding(
                        path=sf.path, line=call.lineno,
                        col=call.col_offset, code=self.code,
                        message=(
                            "jax.jit constructed in the step() closure — "
                            "legs are compiled at init/warmup and cached, "
                            "never per step"
                        ),
                    )

    def _calls_with_loop_flag(self, tree: ast.Module):
        out: List[Tuple[ast.Call, bool]] = []
        loops = (ast.For, ast.While, ast.AsyncFor,
                 # comprehensions ARE loops: [jax.jit(f) for f in fns]
                 # builds a leg per element
                 ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)

        def visit(node: ast.AST, in_loop: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    # decorators + argument defaults evaluate at DEF time
                    # — inside the loop if the def is; the body only runs
                    # when called
                    defaults = [d for d in child.args.kw_defaults if d]
                    defaults += child.args.defaults
                    for expr in list(child.decorator_list) + defaults:
                        for call in iter_calls(expr):
                            out.append((call, in_loop))
                    for stmt in child.body:
                        # the stmt may itself BE a loop — its loop-ness is
                        # normally computed when recursing into a child,
                        # which this direct visit bypasses
                        visit(stmt, isinstance(stmt, loops))
                    continue
                child_in_loop = in_loop
                if isinstance(child, loops):
                    child_in_loop = True
                elif isinstance(child, ast.Lambda):
                    # a lambda body runs later, like a def's
                    child_in_loop = False
                if isinstance(child, ast.Call):
                    out.append((child, in_loop))
                visit(child, child_in_loop)

        visit(tree, False)
        return out

    def _is_jit_construction(self, call: ast.Call) -> bool:
        d = call_name(call)
        if d in self._JIT:
            return True
        # functools.partial(jax.jit, ...) — the decorator idiom
        if d in ("partial", "functools.partial") and call.args:
            return dotted_name(call.args[0]) in self._JIT
        return False
