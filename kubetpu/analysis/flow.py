"""Dataflow layer under the Round-13 rules (KTP007–KTP010).

PR 7's engine pins invariants by *name matching* single lines; this
module is the step up to *flow*: a per-function CFG with a forward
may-taint analysis (KTP007's implicit-sync tracking), a whole-project
lock-acquisition graph (KTP008's deadlock cycles), and a thread-role
model separating wire-handler threads from the step/reconcile loops
(KTP009's escape analysis). Everything here is rule-agnostic machinery;
the rules in ``rules_flow.py`` supply the sources/sinks/policies.

Design constraints, matching ``core``:

- **stdlib only**, one ``ast`` pass per consumer over already-parsed
  trees — no jax, no imports of the linted code;
- **conservative over clever**: the taint engine is a may-analysis
  (union at joins, monotone transfer — it always converges), the lock
  graph resolves only receivers it can type (``self``, attributes whose
  class is assigned in ``__init__``, the wire servers' ``alias = self``
  closure idiom). A receiver we cannot type contributes nothing — rules
  built on this model miss, they do not spray false positives;
- **shared shape**: the class-index/inheritance walk mirrors
  ``rules_device.hot_closure`` so the hot-path closure and the thread
  model agree about who overrides what.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from kubetpu.analysis.core import Project, SourceFile, call_name, dotted_name

def walk_skip_nested(node: ast.AST) -> Iterable[ast.AST]:
    """``ast.walk`` that does not descend into nested function/lambda
    bodies — a nested def is a BINDING at this level; its body runs on
    some later call, not under the enclosing statement's locks or taint
    environment."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            stack.append(c)


# ---------------------------------------------------------------------------
# control-flow graph
# ---------------------------------------------------------------------------


@dataclass
class Block:
    """One basic block: straight-line statements + successor indices.
    Compound statements (If/While/For/With/Try) appear as their OWN
    entry — the "header" — so an analysis sees their test/iter with the
    environment that reaches it; their bodies live in successor blocks."""

    idx: int
    stmts: List[ast.stmt] = field(default_factory=list)
    succs: Set[int] = field(default_factory=set)


class CFG:
    """Statement-level control-flow graph of one function body."""

    def __init__(self) -> None:
        self.blocks: List[Block] = []
        # try-body -> handler edges: control may leave MID-block (any
        # statement can raise), so a flow analysis must propagate the
        # union of the block's intermediate states, not its final one
        self.exceptional: Set[Tuple[int, int]] = set()
        self.entry = self._new()
        self.exit = self._new()

    def _new(self) -> int:
        b = Block(idx=len(self.blocks))
        self.blocks.append(b)
        return b.idx

    def preds(self) -> Dict[int, Set[int]]:
        out: Dict[int, Set[int]] = {b.idx: set() for b in self.blocks}
        for b in self.blocks:
            for s in b.succs:
                out[s].add(b.idx)
        return out


class _CfgBuilder:
    """Builds a CFG from a function body. Loops get back edges, breaks
    and continues resolve against a loop stack, every statement of a
    ``try`` body may jump to every handler (exceptions are unpredictable
    — the conservative over-approximation a may-analysis wants), and
    ``return``/``raise`` edge to the synthetic exit."""

    def __init__(self) -> None:
        self.cfg = CFG()
        self.cur = self.cfg.entry
        # (continue_target, break_target) innermost-last
        self.loops: List[Tuple[int, int]] = []

    def _edge(self, a: int, b: int) -> None:
        self.cfg.blocks[a].succs.add(b)

    def _start(self, pred: Optional[int] = None) -> int:
        b = self.cfg._new()
        if pred is not None:
            self._edge(pred, b)
        return b

    def build(self, body: Sequence[ast.stmt]) -> CFG:
        self.visit_body(body)
        self._edge(self.cur, self.cfg.exit)
        return self.cfg

    def visit_body(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            self.visit_stmt(stmt)

    def visit_stmt(self, stmt: ast.stmt) -> None:
        blocks = self.cfg.blocks
        if isinstance(stmt, ast.If):
            blocks[self.cur].stmts.append(stmt)   # header: test sees env
            head = self.cur
            join = self.cfg._new()
            self.cur = self._start(head)
            self.visit_body(stmt.body)
            self._edge(self.cur, join)
            if stmt.orelse:
                self.cur = self._start(head)
                self.visit_body(stmt.orelse)
                self._edge(self.cur, join)
            else:
                self._edge(head, join)
            self.cur = join
        elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = self._start(self.cur)
            blocks[head].stmts.append(stmt)       # header: test/iter + bind
            after = self.cfg._new()
            self._edge(head, after)               # zero-iteration path
            self.loops.append((head, after))
            self.cur = self._start(head)
            self.visit_body(stmt.body)
            self._edge(self.cur, head)            # back edge
            self.loops.pop()
            if stmt.orelse:
                self.cur = self._start(after)
                self.visit_body(stmt.orelse)
                self._edge(self.cur, after)
            self.cur = after
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            blocks[self.cur].stmts.append(stmt)   # header: binds as-names
            self.visit_body(stmt.body)
        elif isinstance(stmt, ast.Try):
            # the try body starts its OWN block: its leading simple
            # statements must be inside the exceptional-edge range, not
            # merged into the preceding block (which would carry only
            # post-body state into the handlers)
            body_entry = self._start(self.cur)
            self.cur = body_entry
            self.visit_body(stmt.body)
            body_blocks = list(range(body_entry, len(blocks)))
            body_end = self.cur
            ends = []
            for handler in stmt.handlers:
                h = self.cfg._new()
                if handler.name:
                    # `except E as name:` binds — represent with the
                    # handler node so transfer fns can see it
                    blocks[h].stmts.append(handler)
                # any try-body statement may raise into this handler
                for b in body_blocks:
                    self._edge(b, h)
                    self.cfg.exceptional.add((b, h))
                self.cur = h
                self.visit_body(handler.body)
                ends.append(self.cur)
            if stmt.orelse:
                self.cur = body_end
                self.visit_body(stmt.orelse)
                body_end = self.cur
            join = self._start(body_end)
            for e in ends:
                self._edge(e, join)
            self.cur = join
            if stmt.finalbody:
                self.visit_body(stmt.finalbody)
        elif isinstance(stmt, (ast.Return, ast.Raise)):
            blocks[self.cur].stmts.append(stmt)
            self._edge(self.cur, self.cfg.exit)
            self.cur = self.cfg._new()            # unreachable continuation
        elif isinstance(stmt, ast.Break):
            if self.loops:
                self._edge(self.cur, self.loops[-1][1])
            self.cur = self.cfg._new()
        elif isinstance(stmt, ast.Continue):
            if self.loops:
                self._edge(self.cur, self.loops[-1][0])
            self.cur = self.cfg._new()
        else:
            # simple statement (incl. nested def/class: a binding, not a
            # call — nested bodies are analyzed as their own functions)
            blocks[self.cur].stmts.append(stmt)


def build_cfg(func: ast.AST) -> CFG:
    """CFG of *func*'s body (FunctionDef/AsyncFunctionDef)."""
    return _CfgBuilder().build(func.body)


# ---------------------------------------------------------------------------
# taint (forward may-analysis over the CFG)
# ---------------------------------------------------------------------------

# value-preserving wrappers: taint flows THROUGH them unchanged
_TRANSPARENT_CALLS = {"list", "tuple", "sorted", "reversed", "abs", "min",
                      "max", "sum"}


class TaintEngine:
    """Forward may-taint over one function.

    *is_source(call) -> bool* marks producing expressions;
    *sanitizers* is a set of dotted call names whose RESULT is clean
    (e.g. ``np.asarray`` — it syncs, which is KTP001's finding to make,
    and hands back a host array). Tracked variables are plain names and
    ``self.attr`` pseudo-names (strong updates on both: an assignment of
    a clean value kills the taint — the transfer stays monotone in the
    input environment, so the fixpoint converges)."""

    def __init__(self, is_source: Callable[[ast.Call], bool],
                 sanitizers: Optional[Set[str]] = None) -> None:
        self.is_source = is_source
        self.sanitizers = sanitizers or set()

    # -- expression taint ----------------------------------------------------

    def expr_tainted(self, node: ast.AST, env: Set[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in env
        if isinstance(node, ast.Attribute):
            d = dotted_name(node)
            if d is not None and d in env:
                return True
            return self.expr_tainted(node.value, env)
        if isinstance(node, ast.Call):
            d = call_name(node)
            if d is not None and d in self.sanitizers:
                return False
            if self.is_source(node):
                return True
            parts: List[ast.AST] = list(node.args)
            parts += [kw.value for kw in node.keywords]
            # a method on a tainted receiver stays tainted (mask.any())
            if isinstance(node.func, ast.Attribute):
                parts.append(node.func.value)
            return any(self.expr_tainted(p, env) for p in parts)
        if isinstance(node, ast.Lambda):
            return False                      # body runs later, elsewhere
        # generic: any tainted sub-expression taints the whole
        return any(self.expr_tainted(c, env)
                   for c in ast.iter_child_nodes(node)
                   if isinstance(c, (ast.expr, ast.comprehension,
                                     ast.FormattedValue)))

    # -- statement transfer --------------------------------------------------

    @staticmethod
    def _target_keys(target: ast.AST) -> List[str]:
        """Variable keys a target binds: names, self.attr pseudo-names,
        elements of tuple targets; subscript targets key their base (a
        tainted store into a container taints the container)."""
        out: List[str] = []
        stack = [target]
        while stack:
            t = stack.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
            elif isinstance(t, ast.Starred):
                stack.append(t.value)
            elif isinstance(t, ast.Subscript):
                stack.append(t.value)
            elif isinstance(t, ast.Name):
                out.append(t.id)
            elif isinstance(t, ast.Attribute):
                d = dotted_name(t)
                if d is not None:
                    out.append(d)
        return out

    def transfer(self, stmt: ast.stmt, env: Set[str]) -> Set[str]:
        env = set(env)
        if isinstance(stmt, ast.Assign):
            t = self.expr_tainted(stmt.value, env)
            for target in stmt.targets:
                sub = isinstance(target, ast.Subscript)
                for key in self._target_keys(target):
                    if t:
                        env.add(key)
                    elif not sub:     # container base survives clean store
                        env.discard(key)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            t = self.expr_tainted(stmt.value, env)
            for key in self._target_keys(stmt.target):
                env.add(key) if t else env.discard(key)
        elif isinstance(stmt, ast.AugAssign):
            t = (self.expr_tainted(stmt.value, env)
                 or self.expr_tainted(stmt.target, env))
            for key in self._target_keys(stmt.target):
                env.add(key) if t else env.discard(key)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            t = self.expr_tainted(stmt.iter, env)
            for key in self._target_keys(stmt.target):
                env.add(key) if t else env.discard(key)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is None:
                    continue
                t = self.expr_tainted(item.context_expr, env)
                for key in self._target_keys(item.optional_vars):
                    env.add(key) if t else env.discard(key)
        elif isinstance(stmt, ast.ExceptHandler):
            if stmt.name:
                env.discard(stmt.name)
        elif isinstance(stmt, ast.Delete):
            for t_ in stmt.targets:
                for key in self._target_keys(t_):
                    env.discard(key)
        return env

    # -- fixpoint ------------------------------------------------------------

    def run(self, func: ast.AST) -> Dict[int, Set[str]]:
        """{id(stmt): tainted-variable set BEFORE that statement} for
        every statement placed in the CFG (compound headers included)."""
        cfg = build_cfg(func)
        preds = cfg.preds()
        block_in: Dict[int, Set[str]] = {b.idx: set() for b in cfg.blocks}

        def block_out(p: int, exceptional: bool) -> Set[str]:
            """State leaving block *p*. A NORMAL edge carries the state
            after every statement ran; an EXCEPTIONAL edge (try-body ->
            handler) may fire mid-block, so it carries the UNION of
            every intermediate state — taint killed later in the try
            body must still reach the handler."""
            acc = set(block_in[p])
            union = set(acc)
            for s in cfg.blocks[p].stmts:
                acc = self.transfer(s, acc)
                union |= acc
            return union if exceptional else acc

        changed = True
        while changed:
            changed = False
            for b in cfg.blocks:
                env: Set[str] = set()
                for p in preds[b.idx]:
                    env |= block_out(p, (p, b.idx) in cfg.exceptional)
                if env != block_in[b.idx]:
                    # joins only ever union and transfer is monotone, so
                    # envs grow toward the fixpoint
                    block_in[b.idx] = env
                    changed = True
        before: Dict[int, Set[str]] = {}
        for b in cfg.blocks:
            env = block_in[b.idx]
            for s in b.stmts:
                before[id(s)] = env
                env = self.transfer(s, env)
        return before


# ---------------------------------------------------------------------------
# whole-project class index (shared by the lock graph + thread model)
# ---------------------------------------------------------------------------


class ClassIndex:
    """Every class in the project by name, with inheritance-aware method
    resolution and a light attribute-type map (``self.X = ClassName(...)``
    anywhere in the class body types X as ClassName). Names are assumed
    project-unique — true today, and a duplicate would only blur the lock
    graph toward MORE edges, never fewer findings silently."""

    def __init__(self, project: Project) -> None:
        self.classes: Dict[str, Tuple[str, ast.ClassDef]] = {}
        for sf in project:
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    self.classes.setdefault(node.name, (sf.path, node))
        self._methods: Dict[str, Dict[str, ast.AST]] = {}
        self._attr_types: Dict[str, Dict[str, str]] = {}

    def methods(self, cls: str) -> Dict[str, ast.AST]:
        if cls not in self._methods:
            out: Dict[str, ast.AST] = {}
            hit = self.classes.get(cls)
            if hit is not None:
                for item in hit[1].body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        out[item.name] = item
            self._methods[cls] = out
        return self._methods[cls]

    def mro(self, cls: str) -> List[str]:
        """Breadth-first linearization over base-class NAMES known to the
        project (external bases contribute nothing)."""
        seen: List[str] = []
        queue = [cls]
        while queue:
            name = queue.pop(0)
            if name in seen:
                continue
            seen.append(name)
            hit = self.classes.get(name)
            if hit is not None:
                for b in hit[1].bases:
                    d = dotted_name(b)
                    if d is not None:
                        queue.append(d.split(".")[-1])
        return seen

    def resolve(self, cls: str, method: str) -> Optional[Tuple[str, str, ast.AST]]:
        """(defining class, path, node) for *method* through *cls*'s MRO."""
        for name in self.mro(cls):
            node = self.methods(name).get(method)
            if node is not None:
                return name, self.classes[name][0], node
        return None

    def attr_type(self, cls: str, attr: str) -> Optional[str]:
        """Class name of ``self.<attr>`` when some method of *cls* (or a
        base) assigns it ``ClassName(...)`` for a project class."""
        for name in self.mro(cls):
            types = self._class_attr_types(name)
            if attr in types:
                return types[attr]
        return None

    def _class_attr_types(self, cls: str) -> Dict[str, str]:
        if cls not in self._attr_types:
            out: Dict[str, str] = {}
            for node in self.methods(cls).values():
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Assign):
                        continue
                    if not isinstance(sub.value, ast.Call):
                        continue
                    ctor = call_name(sub.value)
                    if ctor is None:
                        continue
                    ctor = ctor.split(".")[-1]
                    if ctor not in self.classes:
                        continue
                    for t in sub.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            out.setdefault(t.attr, ctor)
            self._attr_types[cls] = out
        return self._attr_types[cls]


# ---------------------------------------------------------------------------
# lock model (KTP008)
# ---------------------------------------------------------------------------

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
# Condition() defaults to an RLock; re-acquiring on the same thread is fine
_REENTRANT_CTORS = {"RLock", "Condition"}


@dataclass
class LockSite:
    path: str
    line: int
    col: int
    where: str          # "Class.method" holding the edge


class LockModel:
    """Project-wide lock inventory + ordering graph.

    Nodes are ``Class.attr`` lock ids. An edge ``a -> b`` means some
    code path acquires *b* while holding *a* (nested ``with`` or a call
    chain the class index can type). ``reentrant`` marks RLock/Condition
    ids; re-acquiring those on one thread is legal."""

    def __init__(self, index: ClassIndex) -> None:
        self.index = index
        self.locks: Dict[str, bool] = {}        # id -> reentrant?
        self.edges: Dict[Tuple[str, str], LockSite] = {}
        self.self_cycles: List[Tuple[str, LockSite]] = []
        self._acquires_memo: Dict[Tuple[str, str], Set[str]] = {}

    # -- inventory -----------------------------------------------------------

    def _collect_locks(self) -> None:
        for cls, (_, node) in self.index.classes.items():
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign):
                    continue
                if not isinstance(sub.value, ast.Call):
                    continue
                ctor = call_name(sub.value)
                if ctor is None:
                    continue
                short = ctor.split(".")[-1]
                if short not in _LOCK_CTORS:
                    continue
                for t in sub.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        self.locks[f"{cls}.{t.attr}"] = (
                            short in _REENTRANT_CTORS)

    def lock_id(self, cls: str, attr: str) -> Optional[str]:
        """The lock id ``self.<attr>`` names inside *cls* (inheritance-
        aware: the id belongs to the DEFINING class so every subclass
        shares one node)."""
        for name in self.index.mro(cls):
            lid = f"{name}.{attr}"
            if lid in self.locks:
                return lid
        return None

    # -- acquisition summaries ----------------------------------------------

    def _with_lock_ids(self, cls: str, stmt: ast.AST) -> List[str]:
        out = []
        for item in stmt.items:
            d = dotted_name(item.context_expr)
            if d is None and isinstance(item.context_expr, ast.Call):
                d = dotted_name(item.context_expr.func)
            if d is None or "." not in d:
                continue
            base, attr = d.split(".", 1)
            if base != "self" or "." in attr:
                continue
            lid = self.lock_id(cls, attr)
            if lid is not None:
                out.append(lid)
        return out

    def acquires(self, cls: str, method: str,
                 _stack: Optional[Set[Tuple[str, str]]] = None) -> Set[str]:
        """Lock ids calling ``cls.method`` may acquire, transitively
        through self-calls and typed-attribute calls. ``*_locked``
        methods run with the caller already holding the lock — their own
        ``with`` acquisitions (if any) still count."""
        key = (cls, method)
        if key in self._acquires_memo:
            return self._acquires_memo[key]
        stack = _stack or set()
        if key in stack:
            return set()
        stack = stack | {key}
        hit = self.index.resolve(cls, method)
        out: Set[str] = set()
        if hit is not None:
            _, _, node = hit
            # skip nested defs: a callback defined here runs later, on
            # some other call path — charging its acquisitions to THIS
            # method would fabricate edges (and deadlocks) that cannot
            # happen
            for sub in walk_skip_nested(node):
                if sub is node:
                    continue
                if isinstance(sub, (ast.With, ast.AsyncWith)):
                    out.update(self._with_lock_ids(cls, sub))
                elif isinstance(sub, ast.Call):
                    callee = self._typed_callee(cls, sub)
                    if callee is not None:
                        out |= self.acquires(*callee, _stack=stack)
        self._acquires_memo[key] = out
        return out

    def _typed_callee(self, cls: str,
                      call: ast.Call) -> Optional[Tuple[str, str]]:
        """(class, method) for calls the index can type: ``self.m()``,
        ``super().m()``, ``self.attr.m()`` with a typed attr."""
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        v = f.value
        if isinstance(v, ast.Name) and v.id == "self":
            return (cls, f.attr) if self.index.resolve(cls, f.attr) else None
        if (isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                and v.func.id == "super"):
            return (cls, f.attr) if self.index.resolve(cls, f.attr) else None
        if (isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name)
                and v.value.id == "self"):
            t = self.index.attr_type(cls, v.attr)
            if t is not None and self.index.resolve(t, f.attr):
                return (t, f.attr)
        return None

    # -- edge walk -----------------------------------------------------------

    def build(self, project: Project) -> "LockModel":
        self._collect_locks()
        for cls, (path, node) in self.index.classes.items():
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._walk(cls, path, f"{cls}.{item.name}",
                               item.body, held=())
        return self

    def _walk(self, cls: str, path: str, where: str,
              body: Sequence[ast.stmt], held: Tuple[str, ...]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired = self._with_lock_ids(cls, stmt)
                inner = held
                for lid in acquired:
                    site = LockSite(path, stmt.lineno, stmt.col_offset, where)
                    if lid in inner and not self.locks.get(lid, False):
                        self.self_cycles.append((lid, site))
                    for h in inner:
                        if h != lid:
                            self.edges.setdefault((h, lid), site)
                    inner = inner + (lid,)
                self._walk(cls, path, where, stmt.body, inner)
                continue
            # calls made while holding locks: their transitive
            # acquisitions order after every held lock (nested defs are
            # bindings — their bodies run on some later call path, not
            # under these locks)
            if held:
                for sub in walk_skip_nested(stmt):
                    if not isinstance(sub, ast.Call):
                        continue
                    callee = self._typed_callee(cls, sub)
                    if callee is None:
                        continue
                    site = LockSite(path, sub.lineno, sub.col_offset, where)
                    for lid in self.acquires(*callee):
                        if lid in held and not self.locks.get(lid, False):
                            self.self_cycles.append((lid, site))
                        for h in held:
                            if h != lid:
                                self.edges.setdefault((h, lid), site)
            for sub_body in self._nested_bodies(stmt):
                self._walk(cls, path, where, sub_body, held)

    @staticmethod
    def _nested_bodies(stmt: ast.stmt) -> Iterable[Sequence[ast.stmt]]:
        for attr in ("body", "orelse", "finalbody"):
            b = getattr(stmt, attr, None)
            if b and not isinstance(stmt, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.ClassDef)):
                yield b
        for h in getattr(stmt, "handlers", ()):
            yield h.body

    # -- cycles --------------------------------------------------------------

    def cycles(self) -> List[Tuple[List[str], LockSite]]:
        """Ordering cycles: [(lock-id path a -> b -> ... -> a, site of one
        participating edge)]. Each cycle reports once, keyed by its node
        set. Single-lock re-acquisition lands in ``self_cycles``."""
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
        seen_sets: Set[frozenset] = set()
        out: List[Tuple[List[str], LockSite]] = []

        def dfs(start: str, node: str, path: List[str],
                on_path: Set[str]) -> None:
            for nxt in sorted(graph.get(node, ())):
                if nxt == start and len(path) > 1:
                    key = frozenset(path)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        out.append((path + [start],
                                    self.edges[(node, start)]))
                elif nxt not in on_path and nxt > start:
                    # only walk ids lexically above the start: each cycle
                    # is found from its smallest node exactly once
                    dfs(start, nxt, path + [nxt], on_path | {nxt})

        for start in sorted(graph):
            dfs(start, start, [start], {start})
        return out


def build_lock_model(project: Project,
                     index: Optional[ClassIndex] = None) -> LockModel:
    return LockModel(index or ClassIndex(project)).build(project)


# ---------------------------------------------------------------------------
# thread-role model (KTP009)
# ---------------------------------------------------------------------------

# entry points of the wire-handler role: stdlib http.server dispatch
HANDLER_ROOTS = ("do_GET", "do_POST", "do_DELETE", "do_PUT", "do_PATCH")
# entry points of the step/reconcile-loop role on a server class
LOOP_ROOTS = ("step", "poll_once", "_poll_once", "_poll_loop", "reconcile",
              "run")


@dataclass
class Access:
    attr: str
    path: str
    line: int
    col: int
    locked: bool
    where: str


@dataclass
class ServerThreads:
    """One server class with an embedded wire handler: who writes what
    from handler threads, who reads what from the loop role."""

    server: str                       # server class name
    handler_writes: List[Access] = field(default_factory=list)
    loop_reads: List[Access] = field(default_factory=list)


class ThreadModel:
    """Finds the wire-server idiom both stdlib servers use:

        class Server:
            def __init__(self):
                alias = self
                class Handler(BaseHTTPRequestHandler):
                    def do_GET(self):           # handler THREAD role
                        alias.attr = ...        # mutates server state
                        alias.method(...)       # or via server methods
            def step/_poll_loop(self):          # loop THREAD role
                read self.attr

    Every method of the nested handler class is handler-role (do_* are
    just the dispatch entries; ``run_idempotent(self._leg)`` style
    indirection reaches the rest). Server methods invoked from handler
    code join the role transitively. Lock tracking recognizes both
    ``with alias._lock:`` in handler code and ``with self._lock:``
    inside server methods; ``*_locked`` methods count as locked."""

    def __init__(self, project: Project, index: Optional[ClassIndex] = None,
                 lock_model: Optional[LockModel] = None) -> None:
        self.index = index or ClassIndex(project)
        self.locks = lock_model or build_lock_model(project, self.index)
        self.servers: List[ServerThreads] = []
        self._writes_memo: Dict[Tuple[str, str], List[Tuple[str, ast.AST, bool, str]]] = {}
        self._build(project)

    # -- discovery -----------------------------------------------------------

    def _build(self, project: Project) -> None:
        for sf in project:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for meth in node.body:
                    if not isinstance(meth, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                        continue
                    for inner in ast.walk(meth):
                        if (isinstance(inner, ast.ClassDef)
                                and any(m in HANDLER_ROOTS
                                        for m in (i.name for i in inner.body
                                                  if isinstance(i, ast.FunctionDef)))):
                            alias = self._self_alias(meth, inner)
                            self.servers.append(self._analyze(
                                sf, node.name, meth, inner, alias))

    @staticmethod
    def _self_alias(enclosing: ast.AST, handler: ast.ClassDef) -> Optional[str]:
        """The ``alias = self`` name handler code reaches the server by."""
        for stmt in ast.walk(enclosing):
            if (isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Name)
                    and stmt.value.id == "self"):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        return t.id
        return None

    # -- role analyses -------------------------------------------------------

    def _analyze(self, sf: SourceFile, server: str, enclosing: ast.AST,
                 handler: ast.ClassDef, alias: Optional[str]) -> ServerThreads:
        st = ServerThreads(server=server)
        if alias is not None:
            for meth in handler.body:
                if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._handler_walk(
                        sf, server, alias, f"{server}.Handler.{meth.name}",
                        meth.body, held=False, out=st.handler_writes)
        # loop role: methods reachable from the loop roots via self-calls
        # — resolved per CONCRETE class, across the server's subclasses
        # too (a subclass inherits the handler, and its step/reconcile
        # override reads the same shared attributes — the cross-module
        # escape KTP009 exists to catch)
        for concrete in self._subclasses_of(server):
            for acc in self._loop_reads(concrete):
                st.loop_reads.append(acc)
        return st

    def _subclasses_of(self, cls: str) -> List[str]:
        return [name for name in self.index.classes
                if cls in self.index.mro(name)]

    def _is_server_lock_with(self, server: str, alias: Optional[str],
                             stmt: ast.AST) -> bool:
        for item in stmt.items:
            d = dotted_name(item.context_expr)
            if d is None and isinstance(item.context_expr, ast.Call):
                d = dotted_name(item.context_expr.func)
            if d is None or "." not in d:
                continue
            base, attr = d.split(".", 1)
            if "." in attr:
                continue
            if base in ("self", alias) and self.locks.lock_id(server, attr):
                return True
        return False

    def _handler_walk(self, sf: SourceFile, server: str, alias: str,
                      where: str, body: Sequence[ast.stmt], held: bool,
                      out: List[Access]) -> None:
        """Collect server-state writes made by handler-role code: direct
        ``alias.attr = ...`` stores and, transitively, the self-attribute
        writes of every server method the handler invokes (or merely
        references — ``run_idempotent(self, ..., self._leg)`` passes the
        leg as a value; any referenced handler method joins the role)."""
        for stmt in body:
            inner_held = held
            if (isinstance(stmt, (ast.With, ast.AsyncWith))
                    and self._is_server_lock_with(server, alias, stmt)):
                inner_held = True
            for t, node, aug in self._alias_writes(stmt, alias):
                out.append(Access(attr=t, path=sf.path, line=node.lineno,
                                  col=node.col_offset, locked=held,
                                  where=where))
            for call in self._direct_calls(stmt):
                f = call.func
                if (isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and f.value.id == alias):
                    for (attr, wnode, wlocked, wwhere) in self._method_writes(
                            server, f.attr):
                        out.append(Access(
                            attr=attr, path=self._method_path(server, f.attr),
                            line=wnode.lineno, col=wnode.col_offset,
                            locked=wlocked or held, where=wwhere))
            for sub_body in LockModel._nested_bodies(stmt):
                self._handler_walk(sf, server, alias, where, sub_body,
                                   inner_held, out)

    @staticmethod
    def _direct_calls(stmt: ast.stmt) -> Iterable[ast.Call]:
        """Calls in *stmt* outside nested with/if bodies (those recurse
        via _nested_bodies with the right held flag)."""
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            roots: List[ast.AST] = [i.context_expr for i in stmt.items]
        elif isinstance(stmt, (ast.If, ast.While)):
            roots = [stmt.test]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            roots = [stmt.iter]
        elif isinstance(stmt, ast.Try):
            roots = []
        else:
            roots = [stmt]
        for r in roots:
            for sub in ast.walk(r):
                if isinstance(sub, ast.Call):
                    yield sub

    @staticmethod
    def _alias_writes(stmt: ast.stmt,
                      alias: str) -> List[Tuple[str, ast.AST, bool]]:
        out = []
        if isinstance(stmt, (ast.With, ast.AsyncWith, ast.If, ast.While,
                             ast.For, ast.AsyncFor, ast.Try)):
            return out      # bodies recurse separately with held tracking
        for sub in ast.walk(stmt):
            targets: List[ast.AST] = []
            if isinstance(sub, ast.Assign):
                targets = list(sub.targets)
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                targets = [sub.target]
            elif isinstance(sub, ast.Delete):
                targets = list(sub.targets)
            for t in targets:
                while isinstance(t, ast.Subscript):
                    t = t.value
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == alias):
                    out.append((t.attr, sub, isinstance(sub, ast.AugAssign)))
        return out

    def _method_path(self, cls: str, method: str) -> str:
        hit = self.index.resolve(cls, method)
        return hit[1] if hit is not None else ""

    def _method_writes(self, cls: str, method: str,
                       _stack: Optional[Set[Tuple[str, str]]] = None
                       ) -> List[Tuple[str, ast.AST, bool, str]]:
        """[(attr, node, locked, where)] self-attribute writes performed
        by ``cls.method`` and its transitive self-calls. ``*_locked``
        methods' writes count as locked (caller-holds convention)."""
        key = (cls, method)
        if key in self._writes_memo:
            return self._writes_memo[key]
        stack = _stack or set()
        if key in stack:
            return []
        stack = stack | {key}
        hit = self.index.resolve(cls, method)
        out: List[Tuple[str, ast.AST, bool, str]] = []
        if hit is not None:
            owner, path, node = hit
            body_locked = method.endswith("_locked")
            where = f"{owner}.{method}"

            def walk(body: Sequence[ast.stmt], held: bool) -> None:
                for stmt in body:
                    inner = held
                    if (isinstance(stmt, (ast.With, ast.AsyncWith))
                            and self._is_server_lock_with(cls, None, stmt)):
                        inner = True
                    for (attr, wnode, _aug) in self._alias_writes(stmt, "self"):
                        out.append((attr, wnode, held or body_locked, where))
                    for call in self._direct_calls(stmt):
                        f = call.func
                        if (isinstance(f, ast.Attribute)
                                and isinstance(f.value, ast.Name)
                                and f.value.id == "self"
                                and self.index.resolve(cls, f.attr)):
                            for (attr, wnode, wlocked, wwhere) in \
                                    self._method_writes(cls, f.attr,
                                                        _stack=stack):
                                out.append((attr, wnode,
                                            wlocked or held or body_locked,
                                            wwhere))
                    for sub_body in LockModel._nested_bodies(stmt):
                        walk(sub_body, inner)

            walk(node.body, False)
        self._writes_memo[key] = out
        return out

    def _loop_reads(self, server: str) -> List[Access]:
        """self-attribute LOADS in methods reachable from the server's
        loop roots via self-calls, with lock tracking."""
        out: List[Access] = []
        visited: Set[Tuple[str, str]] = set()
        queue = [r for r in LOOP_ROOTS
                 if self.index.resolve(server, r) is not None]

        def scan(roots: Sequence[ast.AST], path: str, where: str,
                 held: bool) -> None:
            """ONE implementation of the read/call harvest, fed either a
            whole simple statement or just a compound header's exprs —
            the two positions must never drift apart."""
            for root in roots:
                for sub in ast.walk(root):
                    if (isinstance(sub, ast.Attribute)
                            and isinstance(sub.ctx, ast.Load)
                            and isinstance(sub.value, ast.Name)
                            and sub.value.id == "self"):
                        out.append(Access(
                            attr=sub.attr, path=path, line=sub.lineno,
                            col=sub.col_offset, locked=held, where=where))
                    if isinstance(sub, ast.Call):
                        f = sub.func
                        if (isinstance(f, ast.Attribute)
                                and isinstance(f.value, ast.Name)
                                and f.value.id == "self"
                                and (server, f.attr) not in visited
                                and self.index.resolve(server, f.attr)):
                            visited.add((server, f.attr))
                            queue.append(f.attr)

        def walk(method: str, body: Sequence[ast.stmt], path: str,
                 where: str, held: bool) -> None:
            for stmt in body:
                inner = held
                if (isinstance(stmt, (ast.With, ast.AsyncWith))
                        and self._is_server_lock_with(server, None, stmt)):
                    inner = True
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    scan([i.context_expr for i in stmt.items],
                         path, where, held)
                elif isinstance(stmt, (ast.If, ast.While)):
                    scan([stmt.test], path, where, held)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    scan([stmt.iter], path, where, held)
                elif not isinstance(stmt, ast.Try):
                    scan([stmt], path, where, held)
                for sub_body in LockModel._nested_bodies(stmt):
                    walk(method, sub_body, path, where, inner)

        while queue:
            m = queue.pop(0)
            hit = self.index.resolve(server, m)
            if hit is None:
                continue
            owner, path, node = hit
            if (owner, f"__body__{m}") in visited:
                continue
            visited.add((owner, f"__body__{m}"))
            walk(m, node.body, path, f"{owner}.{m}",
                 held=m.endswith("_locked"))
        return out


def build_thread_model(project: Project,
                       index: Optional[ClassIndex] = None,
                       lock_model: Optional[LockModel] = None) -> ThreadModel:
    return ThreadModel(project, index=index, lock_model=lock_model)


# ---------------------------------------------------------------------------
# per-Project model cache (rules share one index/lock model per run)
# ---------------------------------------------------------------------------


def get_class_index(project: Project) -> ClassIndex:
    idx = getattr(project, "_flow_class_index", None)
    if idx is None:
        idx = ClassIndex(project)
        project._flow_class_index = idx
    return idx


def get_lock_model(project: Project) -> LockModel:
    model = getattr(project, "_flow_lock_model", None)
    if model is None:
        model = build_lock_model(project, get_class_index(project))
        project._flow_lock_model = model
    return model


def get_thread_model(project: Project) -> ThreadModel:
    model = getattr(project, "_flow_thread_model", None)
    if model is None:
        model = build_thread_model(project, index=get_class_index(project),
                                   lock_model=get_lock_model(project))
        project._flow_thread_model = model
    return model
