"""Control-plane rules: wire hygiene, lock discipline, metric grammar.

These guard the PR 2/3 contracts that make the chaos suite meaningful:
every HTTP call rides the one retrying client (so fault injection,
idempotency keys and trace propagation apply to it), shared state
mutates under its lock, and metric names stay a bounded, greppable
grammar.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from kubetpu.analysis.core import (
    Finding,
    Project,
    Rule,
    call_name,
    dotted_name,
    iter_calls,
    keyword_arg,
)


class WireHygieneRule(Rule):
    code = "KTP002"
    name = "wire-hygiene"
    description = (
        "all HTTP through wire/httpcommon (request_json/request_text — "
        "retries, idempotency keys, trace propagation, fault injection); "
        "no raw urllib.request.urlopen elsewhere, and POSTs must carry "
        "an idempotency path"
    )

    # the ONE module allowed to open sockets directly: the shared client
    _URLOPEN_HOME = {"kubetpu/wire/httpcommon.py"}
    _URLOPEN = {"urllib.request.urlopen", "request.urlopen", "urlopen"}

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project:
            for call in iter_calls(sf.tree):
                d = call_name(call)
                if d in self._URLOPEN and sf.path not in self._URLOPEN_HOME:
                    yield Finding(
                        path=sf.path, line=call.lineno, col=call.col_offset,
                        code=self.code,
                        message=(
                            "raw urllib.request.urlopen bypasses the "
                            "retrying client (no retries, no trace "
                            "propagation, no fault injection) — use "
                            "httpcommon.request_json/request_text"
                        ),
                    )
                elif d and d.split(".")[-1] == "request_json":
                    miss = self._post_without_key(call)
                    if miss:
                        yield Finding(
                            path=sf.path, line=call.lineno,
                            col=call.col_offset, code=self.code,
                            message=miss,
                        )

    @staticmethod
    def _post_without_key(call: ast.Call) -> Optional[str]:
        """A request_json call that will issue a POST (payload present or
        method='POST') without an idempotency_key= argument: the client
        gives such a POST exactly one attempt, so a dropped response is
        an outage instead of a retry. Calls that merely FORWARD an outer
        idempotency_key parameter pass (the key expression is whatever
        the caller supplied)."""
        if keyword_arg(call, "idempotency_key") is not None:
            return None
        method = keyword_arg(call, "method")
        is_post = False
        if (isinstance(method, ast.Constant)
                and isinstance(method.value, str)):
            if method.value.upper() in ("GET", "HEAD", "DELETE"):
                return None
            is_post = method.value.upper() == "POST"
        if not is_post:
            payload = None
            if len(call.args) >= 2:
                payload = call.args[1]
            elif keyword_arg(call, "payload") is not None:
                payload = keyword_arg(call, "payload")
            if payload is None or (isinstance(payload, ast.Constant)
                                   and payload.value is None):
                return None
        return (
            "request_json POST without idempotency_key= — the client "
            "gives non-keyed POSTs a single attempt (PR 2 retry-safety "
            "contract); pass a key or make the call a GET"
        )


class LockDisciplineRule(Rule):
    code = "KTP003"
    name = "lock-discipline"
    description = (
        "attributes a class mutates under `with self._lock:` are "
        "lock-guarded shared state — every other write to them must "
        "also hold the lock (obs registry, controller, treecache)"
    )

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project:
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    yield from self._check_class(sf.path, node)

    def _check_class(self, path: str, cls: ast.ClassDef) -> Iterable[Finding]:
        methods = [
            m for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        # pass 1: attributes written while holding self._lock, anywhere
        # outside __init__ (constructors initialize before the lock has
        # any contenders — flagging them would just breed disables)
        guarded: Set[str] = set()
        for m in methods:
            if m.name == "__init__":
                continue
            # a `*_locked` method runs with the caller holding the lock
            # (project convention) — its writes are lock-guarded evidence
            body_locked = m.name.endswith("_locked")
            for write, under in self._writes(m):
                if under or body_locked:
                    guarded.add(write[0])
        if not guarded:
            return
        # pass 2: writes to guarded attributes outside a lock block.
        # `*_locked` methods are skipped — the caller holds the lock.
        for m in methods:
            if m.name == "__init__" or m.name.endswith("_locked"):
                continue
            for (attr, node), under in self._writes(m):
                if under or attr not in guarded:
                    continue
                yield Finding(
                    path=path, line=node.lineno, col=node.col_offset,
                    code=self.code,
                    message=(
                        f"write to `self.{attr}` outside `with "
                        f"self._lock:` — `{cls.name}` mutates this "
                        "attribute under the lock elsewhere, so this "
                        "write races those readers/writers"
                    ),
                )

    def _writes(self, func: ast.AST) -> List[Tuple[Tuple[str, ast.AST], bool]]:
        """[((attr, node), under_lock)] for every `self.X = ...`,
        `self.X op= ...`, `self.X[k] = ...`, `del self.X[...]` in
        *func*, tracking `with self._lock:` nesting."""
        out: List[Tuple[Tuple[str, ast.AST], bool]] = []

        def self_attr(target: ast.AST) -> Optional[str]:
            # unwrap subscripts: self.X[k] mutates the object behind
            # self.X just like assignment replaces it
            while isinstance(target, ast.Subscript):
                target = target.value
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                return target.attr
            return None

        def is_lock_with(w: ast.With) -> bool:
            for item in w.items:
                d = dotted_name(item.context_expr)
                if d in ("self._lock", "self._cv"):
                    return True
                # self._lock() / self._cv-style helper calls
                if isinstance(item.context_expr, ast.Call):
                    dc = dotted_name(item.context_expr.func)
                    if dc in ("self._lock", "self._cv"):
                        return True
            return False

        def visit(node: ast.AST, under: bool) -> None:
            for child in ast.iter_child_nodes(node):
                child_under = under
                if isinstance(child, ast.With) and is_lock_with(child):
                    child_under = True
                if isinstance(child, ast.Assign):
                    for t in child.targets:
                        a = self_attr(t)
                        if a is not None:
                            out.append(((a, child), under))
                elif isinstance(child, (ast.AugAssign, ast.AnnAssign)):
                    a = self_attr(child.target)
                    if a is not None:
                        out.append(((a, child), under))
                elif isinstance(child, ast.Delete):
                    for t in child.targets:
                        a = self_attr(t)
                        if a is not None:
                            out.append(((a, child), under))
                visit(child, child_under)

        visit(func, False)
        return out


# metric names: the project grammar (PR 3), counters end _total
_METRIC_NAME_RE = re.compile(r"^kubetpu_[a-z0-9_]+$")


class MetricHygieneRule(Rule):
    code = "KTP004"
    name = "metric-hygiene"
    description = (
        "metric names are string literals matching kubetpu_[a-z0-9_]+ "
        "(counters end _total); an f-string metric/label name is "
        "unbounded cardinality waiting for traffic — unless every "
        "interpolation is a loop variable over a literal tuple (then "
        "each expansion is validated like a literal)"
    )

    _REGISTERING = {"counter", "gauge", "gauge_fn", "histogram",
                    "attach_histogram"}
    # the framework itself + this package (rule fixtures embed names)
    _EXEMPT = ("kubetpu/obs/registry.py", "kubetpu/analysis/")
    _MAX_EXPANSIONS = 64

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project:
            if sf.path.startswith(self._EXEMPT):
                continue
            bindings = self._literal_loop_bindings(sf.tree)
            for call in iter_calls(sf.tree):
                f = call.func
                if (not isinstance(f, ast.Attribute)
                        or f.attr not in self._REGISTERING
                        or not call.args):
                    continue
                kind = f.attr
                name_arg = call.args[0]
                if isinstance(name_arg, ast.JoinedStr):
                    expansions = self._bounded_expansions(
                        name_arg, bindings.get(id(call), {}))
                    if expansions is not None:
                        # Round-13 flow refinement: every interpolation
                        # is a loop variable over a literal tuple — the
                        # name set is closed; validate each member like
                        # a literal instead of demanding a disable
                        for name in expansions:
                            yield from self._check_literal(
                                sf, call, kind, name)
                        continue
                    yield Finding(
                        path=sf.path, line=call.lineno,
                        col=call.col_offset, code=self.code,
                        message=(
                            f"f-string metric name in .{kind}() — "
                            "interpolated names are unbounded series "
                            "cardinality; use literals, or interpolate "
                            "only loop variables bound to a literal "
                            "tuple (a fixed set the engine cannot see "
                            "gets a justified ktlint disable)"
                        ),
                    )
                elif (isinstance(name_arg, ast.Constant)
                        and isinstance(name_arg.value, str)):
                    yield from self._check_literal(
                        sf, call, kind, name_arg.value)
                else:
                    yield Finding(
                        path=sf.path, line=call.lineno,
                        col=call.col_offset, code=self.code,
                        message=(
                            f"non-literal metric name in .{kind}() — "
                            "names must be auditable at the call site "
                            "(facades that forward caller-validated "
                            "names get a justified ktlint disable)"
                        ),
                    )

    def _check_literal(self, sf, call: ast.Call, kind: str,
                       name: str) -> Iterable[Finding]:
        """Validate one concrete metric name (a string literal, or one
        expansion of a bounded f-string)."""
        if not _METRIC_NAME_RE.match(name):
            yield Finding(
                path=sf.path, line=call.lineno,
                col=call.col_offset, code=self.code,
                message=(
                    f"metric name `{name}` does not match "
                    "kubetpu_[a-z0-9_]+ — one prefix keeps "
                    "the fleet exposition greppable"
                ),
            )
        elif kind == "counter" and not name.endswith("_total"):
            yield Finding(
                path=sf.path, line=call.lineno,
                col=call.col_offset, code=self.code,
                message=(
                    f"counter `{name}` must end `_total` "
                    "(Prometheus counter convention the "
                    "SLO engine keys on)"
                ),
            )

    # -- bounded f-string proof (Round-13) -----------------------------------

    @staticmethod
    def _literal_loop_bindings(tree: ast.Module) -> Dict[int, Dict[str, List[str]]]:
        """{id(call): {loop var: [literal strings]}} for every call,
        carrying the innermost enclosing ``for NAME in (<str literals>)``
        bindings — the scope the bounded-f-string proof may expand."""
        out: Dict[int, Dict[str, List[str]]] = {}

        def literal_items(node: ast.AST) -> Optional[List[str]]:
            if not isinstance(node, (ast.Tuple, ast.List, ast.Set)):
                return None
            vals = []
            for e in node.elts:
                if not (isinstance(e, ast.Constant)
                        and isinstance(e.value, str)):
                    return None
                vals.append(e.value)
            return vals

        def rebinds(body: List[ast.stmt], var: str) -> bool:
            """True when *var* is bound again anywhere in *body* — an
            intervening `key = dyn[key]`, an inner `for key in runtime()`,
            a `with ... as key`, walrus or except-as. Any rebind voids
            the proof for the WHOLE loop (order-insensitive on purpose:
            conservative in the direction of demanding a disable, never
            of accepting an unbounded name)."""
            for stmt in body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        continue       # a nested def's locals are theirs
                    targets: List[ast.AST] = []
                    if isinstance(sub, ast.Assign):
                        targets = list(sub.targets)
                    elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                        targets = [sub.target]
                    elif isinstance(sub, (ast.For, ast.AsyncFor)):
                        targets = [sub.target]
                    elif isinstance(sub, ast.NamedExpr):
                        targets = [sub.target]
                    elif isinstance(sub, (ast.With, ast.AsyncWith)):
                        targets = [i.optional_vars for i in sub.items
                                   if i.optional_vars is not None]
                    elif isinstance(sub, ast.ExceptHandler):
                        if sub.name == var:
                            return True
                    for t in targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name) and n.id == var:
                                return True
            return False

        def visit(node: ast.AST, env: Dict[str, List[str]]) -> None:
            for child in ast.iter_child_nodes(node):
                child_env = env
                if isinstance(child, ast.For) and isinstance(
                        child.target, ast.Name):
                    items = literal_items(child.iter)
                    var = child.target.id
                    child_env = dict(env)
                    if items is not None and not rebinds(
                            list(child.body) + list(child.orelse), var):
                        child_env[var] = items
                    else:
                        # non-literal iter (or a rebind in the body)
                        # SHADOWS any outer binding of the same name —
                        # the stale outer tuple must not vouch for it
                        child_env.pop(var, None)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda)):
                    # a nested def's body runs OUTSIDE the loop binding
                    child_env = {}
                if isinstance(child, ast.Call):
                    out[id(child)] = child_env
                visit(child, child_env)

        visit(tree, {})
        return out

    def _bounded_expansions(self, js: ast.JoinedStr,
                            env: Dict[str, List[str]]) -> Optional[List[str]]:
        """All concrete strings *js* can produce when every interpolated
        value is a loop variable bound to a literal tuple — None when any
        part is unprovable (or the product explodes past the cap)."""
        parts: List[List[str]] = []
        for v in js.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append([v.value])
            elif (isinstance(v, ast.FormattedValue)
                    and v.format_spec is None and v.conversion == -1
                    and isinstance(v.value, ast.Name)
                    and v.value.id in env):
                parts.append(env[v.value.id])
            else:
                return None
        out = [""]
        for choices in parts:
            out = [a + c for a in out for c in choices]
            if len(out) > self._MAX_EXPANSIONS:
                return None
        return out
