"""kubetpu.analysis — the project-specific lint engine (Round-12).

Six rounds of PRs accumulated load-bearing invariants that were only
enforced *dynamically* — by tests that must happen to exercise the
offending path (the PR 5/6 zero-upload monkeypatch pins, the PR 2
"every wire call goes through ``request_json``" contract, the obs
registry's lock discipline, the ``kubetpu_*`` metric grammar). This
package is the static twin: an AST-visitor rule engine that flags a
violation at the line that introduces it, before any test runs.

Surface:

- ``python -m kubetpu.analysis [paths...]`` / ``scripts/lint.py`` /
  ``make lint`` — run the full rule suite, exit non-zero on any
  non-baselined finding;
- findings print as ``path:line:col: KTPnnn message`` (or
  ``--format=json`` for machine consumers like bench_gate-style
  regression diffing);
- ``# ktlint: disable=KTPnnn[,KTPmmm]`` suppresses a finding — trailing
  on the finding's ANCHOR line (the line the report names; a multi-line
  statement anchors to its FIRST line, flake8-style) or on a standalone
  comment directly above it. Every disable in the tree should carry a
  comment saying WHY;
- ``lint_baseline.json`` ratchets pre-existing violations: counts per
  (path, rule) may only shrink. Regenerate deliberately with
  ``make lint-baseline`` after paying debt down, never to admit new
  debt.

Rule catalog (stable codes — tooling may key on them):

====== ===================== =====================================
code   name                  invariant (introduced by)
====== ===================== =====================================
KTP001 hot-path-sync         no host syncs/uploads reachable from
                             serving ``step()`` (PR 5/6 pins)
KTP002 wire-hygiene          all HTTP through ``httpcommon``;
                             POSTs carry idempotency keys (PR 2)
KTP003 lock-discipline       writes to ``self._lock``-guarded
                             attributes stay under the lock (PR 3)
KTP004 metric-hygiene        literal ``kubetpu_*`` metric names,
                             counters end ``_total`` (PR 3/6)
KTP005 determinism           no wall-clock / stdlib ``random`` in
                             device-path ``jobs/`` modules (PR 1)
KTP006 jit-leg-hygiene       ``jax.jit`` legs built once and
                             cached, never per-call/in-loop (PR 6)
====== ===================== =====================================

Stdlib only (``ast`` + ``json``); no jax import — the linter must run
anywhere, including CI boxes with no accelerator stack.
"""

from kubetpu.analysis.core import (  # noqa: F401
    Finding,
    LintResult,
    Rule,
    all_rules,
    run_lint,
)
from kubetpu.analysis.baseline import (  # noqa: F401
    apply_baseline,
    load_baseline,
    write_baseline,
)
