"""Flow-aware rules (Round-13): the KTP007–KTP010 set built on
``analysis.flow``'s CFG/taint engine, lock graph and thread-role model.

Where the PR 7 rules pin single lines by name, these four follow VALUES
and ORDER:

- KTP007 catches the syncs KTP001's explicit-call list can never name —
  a device-produced value (``jnp.*``/``lax.*`` result, a ``self._dev``
  mirror) flowing into ``bool()``/``int()``/``float()``/``len()``, an
  ``if``/``while`` condition, iteration, or an f-string inside the
  serving step() closure. Each of those implicitly blocks on the device.
- KTP008 builds the global lock-ordering graph (nested ``with`` blocks
  plus call chains the class index can type) and flags cycles — and the
  sharper special case, re-acquiring a non-reentrant ``threading.Lock``
  already held on the same call path (instant single-thread deadlock).
- KTP009 is the interprocedural generalization of KTP003: state written
  from wire-handler threads (the ``handle_guarded`` routes) and read in
  the step/reconcile loop must hold the owning lock on the WRITE side.
- KTP010 guards the unglamorous leak: files/sockets opened in ``wire/``
  and ``obs/`` outside a ``with``/try-finally, where an early return or
  raise walks the handle out of scope still open.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from kubetpu.analysis.core import (
    Finding,
    Project,
    Rule,
    call_name,
    dotted_name,
)
from kubetpu.analysis.flow import (
    TaintEngine,
    get_lock_model,
    get_thread_model,
    walk_skip_nested,
)
from kubetpu.analysis.rules_device import hot_closure

# ---------------------------------------------------------------------------
# KTP007 — implicit-device-sync taint
# ---------------------------------------------------------------------------

# device-value producers: jax-namespace array ops + the _dev mirror cache
_SOURCE_PREFIXES = ("jnp.", "jax.numpy.", "lax.", "jax.lax.")
# calls that hand back HOST data (they sync too — but by an explicit,
# greppable name KTP001 already rejects; KTP007 must not double-report)
_SANITIZERS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
               "jax.device_get"}
# host coercions that force the implicit sync when fed a device value
_COERCION_SINKS = {"bool", "int", "float", "len"}


def _is_device_source(call: ast.Call) -> bool:
    d = call_name(call)
    if d is None:
        return False
    if any(d.startswith(p) for p in _SOURCE_PREFIXES):
        return True
    return d in ("self._dev",)


# the engine's skip-nested walker under the name this module grew up
# with; for KTP007 the skip has extra meaning — a nested def inside the
# step closure is a jitted leg (traced code cannot host-sync mid-trace)
_walk_skip_nested = walk_skip_nested


def _stmt_own_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """The expressions a statement evaluates ITSELF (not its nested
    block bodies — those are separate CFG statements with their own
    taint environments)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, ast.Assign):
        return [stmt.value] + list(stmt.targets)
    if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        return [v for v in (stmt.value, stmt.target) if v is not None]
    if isinstance(stmt, ast.Return):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, ast.Expr):
        return [stmt.value]
    if isinstance(stmt, ast.Assert):
        return [v for v in (stmt.test, stmt.msg) if v is not None]
    if isinstance(stmt, ast.Raise):
        return [v for v in (stmt.exc, stmt.cause) if v is not None]
    return []


class ImplicitSyncRule(Rule):
    code = "KTP007"
    name = "implicit-sync-taint"
    description = (
        "device-produced values (jnp./lax. results, self._dev mirrors) "
        "must not flow into bool()/int()/float()/len(), if/while "
        "conditions, iteration, or f-strings inside the serving step() "
        "closure — each implicitly blocks on the device (the syncs "
        "KTP001's explicit-call list cannot name)"
    )

    def check(self, project: Project) -> Iterable[Finding]:
        engine = TaintEngine(_is_device_source, sanitizers=_SANITIZERS)
        emitted: Set[Tuple[str, int, int]] = set()
        for (path, _), (_, qual, node) in sorted(hot_closure(project).items()):
            before = engine.run(node)
            for stmt in self._cfg_stmts(node, before):
                env = before[id(stmt)]
                for f in self._sinks_in_stmt(stmt, env, engine, path, qual):
                    key = (f.path, f.line, f.col)
                    if key not in emitted:
                        emitted.add(key)
                        yield f

    @staticmethod
    def _cfg_stmts(func: ast.AST, before: Dict[int, Set[str]]):
        return [s for s in _walk_skip_nested(func)
                if isinstance(s, ast.stmt) and id(s) in before]

    def _sinks_in_stmt(self, stmt: ast.stmt, env: Set[str],
                       engine: TaintEngine, path: str,
                       qual: str) -> Iterable[Finding]:
        in_condition = isinstance(stmt, (ast.If, ast.While, ast.Assert))

        def finding(node: ast.AST, what: str) -> Finding:
            return Finding(
                path=path, line=node.lineno, col=node.col_offset,
                code=self.code,
                message=(
                    f"implicit device sync: {what} on a device-produced "
                    f"value in `{qual.split('.')[-1]}` (reachable from "
                    f"step() via {qual}) — materialize once via the "
                    "designed route/materialize leg instead"
                ),
            )

        for root in _stmt_own_exprs(stmt):
            if in_condition and engine.expr_tainted(root, env):
                yield finding(root, "branch condition")
                continue
            for sub in _walk_skip_nested(root):
                if isinstance(sub, ast.Call):
                    fn = sub.func
                    if (isinstance(fn, ast.Name)
                            and fn.id in _COERCION_SINKS
                            and any(engine.expr_tainted(a, env)
                                    for a in sub.args)):
                        yield finding(sub, f"`{fn.id}()`")
                elif isinstance(sub, ast.IfExp):
                    if engine.expr_tainted(sub.test, env):
                        yield finding(sub.test, "conditional-expression test")
                elif isinstance(sub, ast.FormattedValue):
                    if engine.expr_tainted(sub.value, env):
                        yield finding(sub, "f-string interpolation")
                elif isinstance(sub, (ast.ListComp, ast.SetComp,
                                      ast.DictComp, ast.GeneratorExp)):
                    for gen in sub.generators:
                        if engine.expr_tainted(gen.iter, env):
                            yield finding(gen.iter, "iteration")
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            if engine.expr_tainted(stmt.iter, env):
                yield finding(stmt.iter, "iteration")


# ---------------------------------------------------------------------------
# KTP008 — lock-order deadlock graph
# ---------------------------------------------------------------------------


class LockOrderRule(Rule):
    code = "KTP008"
    name = "lock-order-deadlock"
    description = (
        "the whole-project lock-acquisition graph (nested `with "
        "self._lock:` blocks + call chains) must stay acyclic, and a "
        "non-reentrant threading.Lock must never be re-acquired on a "
        "call path that already holds it (single-thread deadlock)"
    )

    def check(self, project: Project) -> Iterable[Finding]:
        model = get_lock_model(project)
        emitted: Set[Tuple[str, int, int, str]] = set()
        for lid, site in model.self_cycles:
            key = (site.path, site.line, site.col, lid)
            if key in emitted:
                continue
            emitted.add(key)
            yield Finding(
                path=site.path, line=site.line, col=site.col,
                code=self.code,
                message=(
                    f"re-acquisition of non-reentrant lock `{lid}` on a "
                    f"path that already holds it (via {site.where}) — "
                    "this thread deadlocks itself; split a *_locked "
                    "variant or switch to RLock with a comment on why"
                ),
            )
        for cycle, site in model.cycles():
            key = (site.path, site.line, site.col, "->".join(cycle))
            if key in emitted:
                continue
            emitted.add(key)
            yield Finding(
                path=site.path, line=site.line, col=site.col,
                code=self.code,
                message=(
                    "lock-order cycle "
                    + " -> ".join(f"`{c}`" for c in cycle)
                    + f" (one edge acquired via {site.where}) — two "
                    "threads taking these locks in opposite orders "
                    "deadlock; pick one global order and restructure"
                ),
            )


# ---------------------------------------------------------------------------
# KTP009 — thread-escape (handler-thread writes racing the loop role)
# ---------------------------------------------------------------------------


class ThreadEscapeRule(Rule):
    code = "KTP009"
    name = "thread-escape"
    description = (
        "server attributes written from wire-handler threads (the "
        "handle_guarded do_GET/do_POST routes, directly or via server "
        "methods) and read in the step/reconcile loop must hold the "
        "server's lock at the write — the interprocedural "
        "generalization of KTP003"
    )

    def check(self, project: Project) -> Iterable[Finding]:
        model = get_thread_model(project)
        emitted: Set[Tuple[str, int, int, str]] = set()
        for st in model.servers:
            read_attrs = {a.attr for a in st.loop_reads}
            read_at = {}
            for a in st.loop_reads:
                read_at.setdefault(a.attr, a)
            for w in st.handler_writes:
                if w.locked or w.attr not in read_attrs:
                    continue
                key = (w.path, w.line, w.col, w.attr)
                if key in emitted:
                    continue
                emitted.add(key)
                r = read_at[w.attr]
                yield Finding(
                    path=w.path, line=w.line, col=w.col, code=self.code,
                    message=(
                        f"`{st.server}.{w.attr}` is written from a wire-"
                        f"handler thread ({w.where}) without the server "
                        f"lock, and read by the loop role at "
                        f"{r.path}:{r.line} ({r.where}) — handler "
                        "threads race the loop; take the lock or route "
                        "the mutation through a locked method"
                    ),
                )


# ---------------------------------------------------------------------------
# KTP010 — resource/exception safety in wire/ and obs/
# ---------------------------------------------------------------------------

_OPENERS = {"open", "os.open", "os.fdopen", "socket.socket",
            "socket.create_connection"}
_RESOURCE_SCOPES = ("kubetpu/wire/", "kubetpu/obs/")


class ResourceSafetyRule(Rule):
    code = "KTP010"
    name = "resource-safety"
    description = (
        "files/sockets in wire/ and obs/ must be opened in a `with`, "
        "closed in a try/finally, or handed off (stored on self / "
        "returned) before any early return or raise can leak the handle"
    )

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project:
            if not sf.path.startswith(_RESOURCE_SCOPES):
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from self._check_function(sf.path, node)

    def _check_function(self, path: str,
                        func: ast.AST) -> Iterable[Finding]:
        with_exprs: Set[int] = set()
        # finally blocks run on EVERY path; except handlers only on the
        # raising one — a close that lives only in a handler does not
        # close the normal path, so the two spans are tracked apart
        finally_ranges: List[Tuple[int, int]] = []
        except_ranges: List[Tuple[int, int]] = []
        for sub in _walk_skip_nested(func):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    for c in ast.walk(item.context_expr):
                        with_exprs.add(id(c))
            elif isinstance(sub, ast.Try):
                for blk in sub.finalbody:
                    finally_ranges.append(
                        (blk.lineno, getattr(blk, "end_lineno", blk.lineno)))
                for blk in (s for h in sub.handlers for s in h.body):
                    except_ranges.append(
                        (blk.lineno, getattr(blk, "end_lineno", blk.lineno)))

        def span(ranges):
            return lambda line: any(lo <= line <= hi for lo, hi in ranges)

        # gather per-statement events once, in source order
        stmts = [s for s in _walk_skip_nested(func)
                 if isinstance(s, ast.stmt)]
        for sub in _walk_skip_nested(func):
            if not isinstance(sub, ast.Call):
                continue
            d = call_name(sub)
            if d not in _OPENERS or id(sub) in with_exprs:
                continue
            yield from self._check_open(path, func, sub, stmts,
                                        span(finally_ranges),
                                        span(except_ranges))

    def _check_open(self, path: str, func: ast.AST, call: ast.Call,
                    stmts: Sequence[ast.stmt],
                    in_finally, in_except) -> Iterable[Finding]:
        owner = self._owner_stmt(stmts, call)
        if owner is None:
            return
        name = self._bound_name(owner, call)
        if name is None:
            # inline use: `return open(...)` / `f(open(...))` hands the
            # handle off; a bare `open(...)` expression drops it on the
            # floor with no way to ever close it
            if isinstance(owner, ast.Expr) and owner.value is call:
                yield self._finding(
                    path, call,
                    "handle opened and immediately dropped — nothing can "
                    "ever close it")
            elif (isinstance(owner, ast.Assign)
                  and len(owner.targets) == 1
                  and isinstance(owner.targets[0], ast.Attribute)):
                pass  # self.x = open(...): escapes to the object
            return
        closes: List[int] = []
        escapes = False
        exits: List[int] = []
        for stmt in stmts:
            if stmt.lineno < owner.lineno or stmt is owner:
                continue
            for sub in _walk_skip_nested(stmt):
                if isinstance(sub, (ast.With, ast.AsyncWith)):
                    # `with fh:` (possibly `as g`) delegates the close to
                    # __exit__ — the handle is managed from here on
                    for item in sub.items:
                        if (isinstance(item.context_expr, ast.Name)
                                and item.context_expr.id == name):
                            closes.append(sub.lineno)
                if isinstance(sub, ast.Call):
                    f = sub.func
                    if (isinstance(f, ast.Attribute) and f.attr == "close"
                            and isinstance(f.value, ast.Name)
                            and f.value.id == name):
                        closes.append(sub.lineno)
                    elif any(isinstance(a, ast.Name) and a.id == name
                             for a in list(sub.args)
                             + [k.value for k in sub.keywords]):
                        escapes = True       # handed to another owner
                elif isinstance(sub, ast.Assign):
                    if (isinstance(sub.value, ast.Name)
                            and sub.value.id == name):
                        escapes = True       # stored (self.x = handle, ...)
                elif isinstance(sub, ast.Return):
                    # only returning the HANDLE itself (bare, or as a
                    # tuple/list element) transfers ownership — `return
                    # fh.read()` returns data and leaves fh open
                    v = sub.value
                    elts = ([v] if isinstance(v, ast.Name)
                            else list(getattr(v, "elts", ())))
                    if any(isinstance(n, ast.Name) and n.id == name
                           for n in elts):
                        escapes = True
                    else:
                        exits.append(sub.lineno)
                elif isinstance(sub, ast.Raise):
                    exits.append(sub.lineno)
        if escapes:
            return
        # a close in a finally runs on every path: fully protected
        normal_closes = [c for c in closes if not in_except(c)]
        if any(in_finally(c) for c in closes):
            return
        if not normal_closes:
            where = (" (only the exception path closes it)"
                     if closes else "")
            yield self._finding(
                path, call,
                f"`{name}` is never closed, stored, or returned on the "
                f"normal path out of this function{where}")
            return
        first_close = min(normal_closes)
        leaks = [e for e in exits if owner.lineno < e < first_close]
        if leaks:
            yield self._finding(
                path, call,
                f"`{name}` leaks across the early exit at line "
                f"{leaks[0]} — the close at line {first_close} is not "
                "in a finally; use `with` or try/finally")

    @staticmethod
    def _owner_stmt(stmts: Sequence[ast.stmt],
                    call: ast.Call) -> Optional[ast.stmt]:
        """The innermost simple statement containing *call*."""
        best = None
        for s in stmts:
            if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                              ast.Expr, ast.Return)):
                if any(c is call for c in ast.walk(s)):
                    best = s
        return best

    @staticmethod
    def _bound_name(owner: ast.stmt, call: ast.Call) -> Optional[str]:
        if (isinstance(owner, ast.Assign) and owner.value is call
                and len(owner.targets) == 1
                and isinstance(owner.targets[0], ast.Name)):
            return owner.targets[0].id
        if (isinstance(owner, ast.AnnAssign) and owner.value is call
                and isinstance(owner.target, ast.Name)):
            return owner.target.id
        return None

    def _finding(self, path: str, call: ast.Call, msg: str) -> Finding:
        return Finding(path=path, line=call.lineno, col=call.col_offset,
                       code=self.code, message=msg)
