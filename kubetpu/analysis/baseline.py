"""The baseline ratchet: ``lint_baseline.json``.

Pre-existing violations must not block the build (that would force a
big-bang cleanup before the gate could land), but they must never be a
license to add more. The baseline records, per ``(path, rule-code)``,
how many unsuppressed findings existed when it was last regenerated;
``apply_baseline`` absorbs up to that many findings per key and lets
anything beyond it fail.

Counts — not line numbers — are the key on purpose: an unrelated edit
above a baselined finding moves its line, and a line-keyed baseline
would re-open it as "new" (noise that teaches people to regenerate
reflexively, which defeats the ratchet). A count per (path, code) is
stable under drift and still catches the only thing that matters: MORE
violations of rule X in file Y than the debt on record.

The ratchet direction is social, enforced by review + the meta-test in
``tests/test_analysis.py``: ``make lint-baseline`` rewrites the file
from the current tree, and the diff must only ever shrink counts.
"""

from __future__ import annotations

import json
from typing import Dict, List

BASELINE_VERSION = 1
DEFAULT_BASELINE = "lint_baseline.json"


def load_baseline(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"{path}: not a v{BASELINE_VERSION} lint baseline"
        )
    if not isinstance(data.get("counts"), dict):
        raise ValueError(f"{path}: missing counts map")
    return data


def baseline_from_findings(findings) -> dict:
    """Build the baseline dict for the current tree: unsuppressed
    finding counts per ``path::code`` (suppressed findings are already
    handled at their line — recording them too would double-absorb)."""
    counts: Dict[str, int] = {}
    for f in findings:
        if f.suppressed:
            continue
        key = f"{f.path}::{f.code}"
        counts[key] = counts.get(key, 0) + 1
    return {
        "version": BASELINE_VERSION,
        "comment": (
            "Ratcheted pre-existing lint findings (counts per path::rule)."
            " Regenerate ONLY to shrink: make lint-baseline."
        ),
        "counts": dict(sorted(counts.items())),
    }


def write_baseline(path: str, findings) -> dict:
    data = baseline_from_findings(findings)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return data


def apply_baseline(findings: List, baseline: dict) -> None:
    """Mark up to ``counts[path::code]`` unsuppressed findings per key
    as baselined, in file order (findings arrive sorted by path/line, so
    the absorbed ones are the earliest — matching how debt was counted
    when the baseline was written)."""
    budget = dict(baseline.get("counts", {}))
    for f in findings:
        if f.suppressed:
            continue
        key = f"{f.path}::{f.code}"
        left = budget.get(key, 0)
        if left > 0:
            f.baselined = True
            budget[key] = left - 1


def stale_keys(findings: List, baseline: dict) -> Dict[str, int]:
    """Baseline entries with MORE budget than current findings — debt
    that was paid down without regenerating. Reported so ``make lint``
    can nudge (never fail): a shrinking baseline should be committed."""
    current: Dict[str, int] = {}
    for f in findings:
        if f.suppressed:
            continue
        key = f"{f.path}::{f.code}"
        current[key] = current.get(key, 0) + 1
    out: Dict[str, int] = {}
    for key, budget in baseline.get("counts", {}).items():
        extra = budget - current.get(key, 0)
        if extra > 0:
            out[key] = extra
    return out
