"""``python -m kubetpu.analysis`` — the lint front door.

Exit codes: 0 clean (baselined/suppressed findings allowed), 1 any new
finding, 2 usage errors. Text output is one ``path:line:col: KTPnnn
message`` per finding (editor/CI clickable); ``--format=json`` emits the
full structured result for tooling (finding-count regression diffing,
bench_gate-style).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from kubetpu.analysis import baseline as baseline_mod
from kubetpu.analysis.core import all_rules, run_lint

DEFAULT_PATHS = ("kubetpu", "scripts")


def _find_root(start: Optional[str] = None) -> str:
    """The repo root: nearest ancestor of this package holding the
    kubetpu/ tree (so the CLI works from any CWD inside the checkout)."""
    here = os.path.dirname(os.path.abspath(
        start or os.path.dirname(__file__)))
    cur = here
    while True:
        if os.path.isdir(os.path.join(cur, "kubetpu")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.getcwd()
        cur = parent


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubetpu.analysis",
        description="kubetpu static invariant linter (rules KTP001…)",
    )
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to lint (default: {DEFAULT_PATHS})")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help="lint_baseline.json path (default: <root>/"
                         f"{baseline_mod.DEFAULT_BASELINE}; missing = bare)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (every finding fails)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from this run and exit 0"
                         " — the deliberate ratchet reset (make"
                         " lint-baseline)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule codes to run (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed/baselined findings")
    args = ap.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.code} {r.name}: {r.description}")
        return 0

    if args.write_baseline and (args.rules or args.paths):
        # a scoped run sees only a slice of the findings — writing the
        # baseline from it would silently DROP every other rule's/file's
        # ratchet budget and re-open that debt as "new" on the next run
        print("--write-baseline must regenerate from the FULL default "
              "run; drop --rules/paths", file=sys.stderr)
        return 2

    if args.rules:
        want = {c.strip().upper() for c in args.rules.split(",")}
        unknown = want - {r.code for r in rules}
        if unknown:
            print(f"unknown rule codes: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.code in want]

    root = args.root or _find_root()
    paths = args.paths or [p for p in DEFAULT_PATHS
                           if os.path.exists(os.path.join(root, p))]
    baseline_path = args.baseline or os.path.join(
        root, baseline_mod.DEFAULT_BASELINE)

    baseline = None
    if not args.no_baseline and not args.write_baseline:
        if os.path.exists(baseline_path):
            try:
                baseline = baseline_mod.load_baseline(baseline_path)
            except ValueError as e:
                print(f"bad baseline: {e}", file=sys.stderr)
                return 2

    t0 = time.monotonic()
    result = run_lint(root, paths, rules=rules, baseline=baseline)
    dur = time.monotonic() - t0

    if args.write_baseline:
        data = baseline_mod.write_baseline(baseline_path, result.findings)
        n = sum(data["counts"].values())
        print(f"wrote {baseline_path}: {len(data['counts'])} keys, "
              f"{n} ratcheted findings")
        return 0

    if args.format == "json":
        out = result.to_json()
        out["duration_seconds"] = round(dur, 3)
        print(json.dumps(out, indent=2))
        return 1 if result.active else 0

    shown = result.findings if args.show_suppressed else result.active
    for f in shown:
        tag = ""
        if f.suppressed:
            tag = "  [suppressed]"
        elif f.baselined:
            tag = "  [baselined]"
        print(f.render() + tag)
    summary = (
        f"lint: {len(result.active)} new, {len(result.baselined)} "
        f"baselined, {len(result.suppressed)} suppressed "
        f"({len(rules)} rules, {dur:.1f}s)"
    )
    print(summary, file=sys.stderr)
    if baseline is not None:
        stale = baseline_mod.stale_keys(result.findings, baseline)
        if stale:
            paid = sum(stale.values())
            print(
                f"lint: baseline is stale — {paid} ratcheted finding(s) "
                "no longer exist; commit a shrunk baseline "
                "(make lint-baseline)",
                file=sys.stderr,
            )
    return 1 if result.active else 0


if __name__ == "__main__":
    raise SystemExit(main())
