"""``python -m kubetpu.analysis`` — the lint front door.

Exit codes: 0 clean (baselined/suppressed findings allowed), 1 any new
finding (or, with ``--fail-stale``, a stale baseline), 2 usage errors.
Text output is one ``path:line:col: KTPnnn message`` per finding
(editor/CI clickable); ``--format=json`` emits the full structured
result for tooling (finding-count regression diffing, bench_gate-style);
``--format=github`` emits workflow-command annotations so CI findings
land inline on the PR diff.

``--changed-only`` scopes the REPORT to files git sees as changed
(working tree + index vs ``--diff-base``, default HEAD). The whole
project is still parsed — the flow-aware rules (hot-path closure, lock
graph, thread roles) need global context, and a finding in an unchanged
file can be CAUSED by a changed one — but only findings in changed files
fail the run, so the gate's failure surface scales with the diff, not
the repo.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import List, Optional, Set

from kubetpu.analysis import baseline as baseline_mod
from kubetpu.analysis.core import all_rules, run_lint

DEFAULT_PATHS = ("kubetpu", "scripts")


def _find_root(start: Optional[str] = None) -> str:
    """The repo root: nearest ancestor of this package holding the
    kubetpu/ tree (so the CLI works from any CWD inside the checkout)."""
    here = os.path.dirname(os.path.abspath(
        start or os.path.dirname(__file__)))
    cur = here
    while True:
        if os.path.isdir(os.path.join(cur, "kubetpu")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.getcwd()
        cur = parent


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubetpu.analysis",
        description="kubetpu static invariant linter (rules KTP001…)",
    )
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to lint (default: {DEFAULT_PATHS})")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected)")
    ap.add_argument("--format", choices=("text", "json", "github"),
                    default="text")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only findings in files git sees as "
                         "changed (the full tree is still parsed for "
                         "whole-project context)")
    ap.add_argument("--diff-base", default="HEAD",
                    help="git ref --changed-only diffs against "
                         "(default: HEAD; untracked files always count)")
    ap.add_argument("--fail-stale", action="store_true",
                    help="exit 1 when the baseline holds budget for "
                         "findings that no longer exist (CI mode — a "
                         "paid-down ratchet must be committed)")
    ap.add_argument("--baseline", default=None,
                    help="lint_baseline.json path (default: <root>/"
                         f"{baseline_mod.DEFAULT_BASELINE}; missing = bare)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (every finding fails)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from this run and exit 0"
                         " — the deliberate ratchet reset (make"
                         " lint-baseline)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule codes to run (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed/baselined findings")
    args = ap.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.code} {r.name}: {r.description}")
        return 0

    if args.write_baseline and (args.rules or args.paths
                                or args.changed_only):
        # a scoped run sees only a slice of the findings — writing the
        # baseline from it would silently DROP every other rule's/file's
        # ratchet budget and re-open that debt as "new" on the next run
        print("--write-baseline must regenerate from the FULL default "
              "run; drop --rules/paths/--changed-only", file=sys.stderr)
        return 2

    if args.rules:
        want = {c.strip().upper() for c in args.rules.split(",")}
        unknown = want - {r.code for r in rules}
        if unknown:
            print(f"unknown rule codes: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.code in want]

    root = args.root or _find_root()
    paths = args.paths or [p for p in DEFAULT_PATHS
                           if os.path.exists(os.path.join(root, p))]
    baseline_path = args.baseline or os.path.join(
        root, baseline_mod.DEFAULT_BASELINE)

    baseline = None
    if not args.no_baseline and not args.write_baseline:
        if os.path.exists(baseline_path):
            try:
                baseline = baseline_mod.load_baseline(baseline_path)
            except ValueError as e:
                print(f"bad baseline: {e}", file=sys.stderr)
                return 2

    changed: Optional[Set[str]] = None
    if args.changed_only:
        changed = _changed_files(root, args.diff_base)
        if changed is None:
            print("--changed-only: git diff failed (not a checkout?); "
                  "reporting the full run", file=sys.stderr)

    t0 = time.monotonic()
    result = run_lint(root, paths, rules=rules, baseline=baseline)
    dur = time.monotonic() - t0

    if args.write_baseline:
        data = baseline_mod.write_baseline(baseline_path, result.findings)
        n = sum(data["counts"].values())
        print(f"wrote {baseline_path}: {len(data['counts'])} keys, "
              f"{n} ratcheted findings")
        return 0

    failing = [f for f in result.active
               if changed is None or f.path in changed]
    # staleness is only decidable when the FULL finding set was
    # computed: a --rules/paths scope sees a slice, so every
    # out-of-scope baseline key would read as "paid down" and a clean
    # tree would fail (the same hazard --write-baseline refuses).
    # --changed-only is NOT scoped here — it filters the report, but
    # run_lint still linted the full default paths, so stale_keys over
    # result.findings stays exact.
    scoped = bool(args.rules or args.paths)
    stale = (baseline_mod.stale_keys(result.findings, baseline)
             if baseline is not None and not scoped else {})
    rc = 1 if failing or (args.fail_stale and stale) else 0

    if args.format == "json":
        out = result.to_json()
        out["duration_seconds"] = round(dur, 3)
        out["failing"] = len(failing)
        if changed is not None:
            out["changed_only"] = sorted(changed)
        if stale:
            out["stale_baseline_keys"] = stale
        print(json.dumps(out, indent=2))
        return rc

    if args.format == "github":
        # GitHub workflow commands: CI surfaces each finding inline on
        # the PR diff. Active findings are errors; with
        # --show-suppressed, absorbed/disabled ones annotate as notices.
        for f in failing:
            print(f"::error file={f.path},line={f.line},col={f.col},"
                  f"title={f.code}::{_gh_escape(f.message)}")
        if args.show_suppressed:
            for f in result.findings:
                if not (f.suppressed or f.baselined):
                    continue
                if changed is not None and f.path not in changed:
                    continue
                kind = "suppressed" if f.suppressed else "baselined"
                print(f"::notice file={f.path},line={f.line},col={f.col},"
                      f"title={f.code} {kind}::{_gh_escape(f.message)}")
        if args.fail_stale and stale:
            print("::error title=stale lint baseline::"
                  + _gh_escape(f"{sum(stale.values())} ratcheted "
                               "finding(s) no longer exist; run make "
                               "lint-baseline and commit the shrink"))
        return rc

    shown = result.findings if args.show_suppressed else failing
    for f in shown:
        if changed is not None and f.path not in changed:
            continue
        tag = ""
        if f.suppressed:
            tag = "  [suppressed]"
        elif f.baselined:
            tag = "  [baselined]"
        print(f.render() + tag)
    scope = (f" [{len(changed)} changed files]"
             if changed is not None else "")
    summary = (
        f"lint: {len(failing)} new, {len(result.baselined)} "
        f"baselined, {len(result.suppressed)} suppressed "
        f"({len(rules)} rules, {dur:.1f}s){scope}"
    )
    print(summary, file=sys.stderr)
    if stale:
        paid = sum(stale.values())
        fatal = " (--fail-stale: failing the run)" if args.fail_stale else ""
        print(
            f"lint: baseline is stale — {paid} ratcheted finding(s) "
            "no longer exist; commit a shrunk baseline "
            f"(make lint-baseline){fatal}",
            file=sys.stderr,
        )
    return rc


def _gh_escape(msg: str) -> str:
    """GitHub workflow-command data escaping (the documented set)."""
    return (msg.replace("%", "%25").replace("\r", "%0D")
            .replace("\n", "%0A"))


def _changed_files(root: str, base: str) -> Optional[Set[str]]:
    """LINT-ROOT-relative paths git sees as changed: committed-vs-*base*
    + working tree + index + untracked. None when git is unusable here.

    git prints paths relative to the repo TOPLEVEL; when the lint root
    is a subdirectory of the checkout (a vendored project), findings are
    root-relative — so toplevel paths are re-rooted via ``--show-prefix``
    (changes outside the lint root are dropped: they cannot host a
    finding)."""
    out: Set[str] = set()
    try:
        prefix_run = subprocess.run(
            ["git", "rev-parse", "--show-prefix"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
        if prefix_run.returncode != 0:
            return None
        prefix = prefix_run.stdout.strip()

        def add(p: str) -> None:
            p = p.strip().strip('"')
            if not p:
                return
            if prefix:
                if not p.startswith(prefix):
                    return
                p = p[len(prefix):]
            out.add(p)

        diff = subprocess.run(
            ["git", "diff", "--name-only", base],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
        if diff.returncode != 0:
            return None
        for p in diff.stdout.splitlines():
            add(p)
        status = subprocess.run(
            ["git", "status", "--porcelain", "--untracked-files=all"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
        if status.returncode == 0:
            for line in status.stdout.splitlines():
                p = line[3:]
                if " -> " in p:          # rename: new side is the live file
                    p = p.split(" -> ", 1)[1]
                add(p)
    except (OSError, subprocess.SubprocessError):
        return None
    return out


if __name__ == "__main__":
    raise SystemExit(main())
