from kubetpu.analysis.cli import main

raise SystemExit(main())
