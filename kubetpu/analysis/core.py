"""Engine core: file loading, the rule registry, suppression scanning,
and the one ``run_lint`` entry point every surface (CLI, tests, make)
calls.

Design constraints, in order:

- **whole-project context**: rules see every parsed file at once, not
  one file at a time — the hot-path rule must flatten a class hierarchy
  that spans ``serving.py`` -> ``paged.py`` -> ``spec_serving.py``, and
  the wire rule needs to know which module it is standing in;
- **cheap**: one ``ast.parse`` per file, shared by all rules; the full
  tree (~150 files) lints in low single-digit seconds, well under the
  30s budget ``make lint`` rides in ``make chaos``;
- **suppressable at the line**: ``# ktlint: disable=KTPnnn`` on the
  finding's line or the line directly above. Suppressed findings are
  kept (marked) so ``--show-suppressed`` and the JSON output can audit
  them, but they never fail the run;
- **stdlib only**: the linter must run on machines with no jax.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

_SUPPRESS_RE = re.compile(
    r"#\s*ktlint:\s*disable=([A-Z]{3}[0-9]{3}(?:\s*,\s*[A-Z]{3}[0-9]{3})*)"
)

# directories never worth parsing (build junk, VCS internals)
_SKIP_DIRS = {".git", "__pycache__", "_output", ".pytest_cache", "node_modules"}


@dataclass
class Finding:
    """One rule violation, anchored to the line that introduces it."""

    path: str          # repo-relative, forward slashes
    line: int
    col: int
    code: str          # "KTP001"
    message: str
    suppressed: bool = False   # an inline ktlint: disable covers it
    baselined: bool = False    # absorbed by lint_baseline.json

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"

    def to_json(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "code": self.code,
            "message": self.message,
            "suppressed": self.suppressed,
            "baselined": self.baselined,
        }


@dataclass
class SourceFile:
    """One parsed module plus the per-line suppression index."""

    path: str                  # repo-relative
    source: str
    tree: ast.Module
    # line -> set of codes disabled on that line (trailing comment) or
    # by a standalone comment on the line directly above
    suppressions: Dict[int, set] = field(default_factory=dict)

    def suppressed_at(self, line: int, code: str) -> bool:
        return code in self.suppressions.get(line, set())


class Project:
    """Everything a rule may look at: the parsed files, keyed by
    repo-relative path."""

    def __init__(self, files: Dict[str, SourceFile]) -> None:
        self.files = files

    def get(self, path: str) -> Optional[SourceFile]:
        return self.files.get(path)

    def __iter__(self):
        return iter(self.files.values())


class Rule:
    """Base class. Subclasses set ``code``/``name``/``description`` and
    implement ``check(project) -> iterable of Finding``. Registration is
    by subclassing — ``all_rules()`` instantiates every leaf subclass,
    so a new rule file only needs to be imported to participate."""

    code: str = ""
    name: str = ""
    description: str = ""

    def check(self, project: Project) -> Iterable[Finding]:
        raise NotImplementedError


def _scan_suppressions(source: str) -> Dict[int, set]:
    """Per-line ``# ktlint: disable=`` index. A trailing comment covers
    its own line; a comment on an otherwise code-free line covers the
    NEXT line too (the idiom for statements too long to share a line
    with their justification)."""
    out: Dict[int, set] = {}
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        codes = {c.strip() for c in m.group(1).split(",")}
        out.setdefault(lineno, set()).update(codes)
        if text.lstrip().startswith("#"):    # standalone comment line
            out.setdefault(lineno + 1, set()).update(codes)
    return out


def load_project(root: str, paths: Sequence[str]) -> Project:
    """Parse every ``.py`` under *paths* (files or directories, given
    relative to *root*). Unparseable files are skipped — syntax errors
    are the compiler's job, not the linter's."""
    import os

    files: Dict[str, SourceFile] = {}

    def add(abs_path: str) -> None:
        rel = os.path.relpath(abs_path, root).replace(os.sep, "/")
        try:
            with open(abs_path, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=rel)
        except (OSError, SyntaxError, ValueError):
            return
        files[rel] = SourceFile(
            path=rel, source=source, tree=tree,
            suppressions=_scan_suppressions(source),
        )

    for p in paths:
        abs_p = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(abs_p):
            add(abs_p)
            continue
        for dirpath, dirnames, filenames in os.walk(abs_p):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    add(os.path.join(dirpath, fn))
    return Project(files)


def all_rules() -> List[Rule]:
    """Every registered rule, sorted by code. Importing the rule
    modules here keeps ``core`` import-cycle-free while making
    ``run_lint`` self-contained."""
    from kubetpu.analysis import rules_device, rules_flow, rules_plane  # noqa: F401

    def leaves(cls):
        subs = cls.__subclasses__()
        if not subs:
            return [cls]
        out = []
        for s in subs:
            out.extend(leaves(s))
        return out

    rules = [cls() for cls in leaves(Rule) if cls is not Rule and cls.code]
    rules.sort(key=lambda r: r.code)
    return rules


@dataclass
class LintResult:
    """The full outcome of one run: every finding (suppressed and
    baselined ones marked, not dropped) plus the selection that should
    fail the build."""

    findings: List[Finding]
    rules: List[Rule]

    @property
    def active(self) -> List[Finding]:
        """Findings that fail the run: not suppressed, not baselined."""
        return [f for f in self.findings
                if not f.suppressed and not f.baselined]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    @property
    def baselined(self) -> List[Finding]:
        return [f for f in self.findings if f.baselined]

    def counts(self) -> Dict[str, int]:
        """Unsuppressed finding count per rule code (baselined ones
        included — this is the number the baseline ratchets on)."""
        out: Dict[str, int] = {}
        for f in self.findings:
            if not f.suppressed:
                out[f.code] = out.get(f.code, 0) + 1
        return out

    def to_json(self) -> dict:
        return {
            "version": 1,
            "findings": [f.to_json() for f in self.findings],
            "counts": self.counts(),
            "new": len(self.active),
            "baselined": len(self.baselined),
            "suppressed": len(self.suppressed),
            "rules": [
                {"code": r.code, "name": r.name,
                 "description": r.description}
                for r in self.rules
            ],
        }


def run_lint(
    root: str,
    paths: Sequence[str],
    rules: Optional[Sequence[Rule]] = None,
    baseline: Optional[dict] = None,
) -> LintResult:
    """Parse, run every rule, mark suppressions, apply the baseline
    ratchet. *baseline* is the parsed ``lint_baseline.json`` (or None
    for a bare run)."""
    from kubetpu.analysis.baseline import apply_baseline

    project = load_project(root, paths)
    ruleset = list(rules) if rules is not None else all_rules()
    findings: List[Finding] = []
    for rule in ruleset:
        for f in rule.check(project):
            sf = project.get(f.path)
            if sf is not None and sf.suppressed_at(f.line, f.code):
                f.suppressed = True
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    if baseline is not None:
        apply_baseline(findings, baseline)
    return LintResult(findings=findings, rules=ruleset)


# -- shared AST helpers (used by both rule modules) --------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def iter_calls(node: ast.AST) -> Iterable[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def keyword_arg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None
