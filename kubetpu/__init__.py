"""kubetpu — a TPU-native device-management and topology-aware scheduling framework.

Built from scratch with the capabilities of microsoft/KubeGPU (the reference,
surveyed in SURVEY.md): a device-plugin layer that enumerates accelerator
hardware and advertises it as hierarchical resources, a topology-aware
scheduler that shapes multi-chip pod requests onto the best available
interconnect topology, and a core harness (scheduler loop + group/gang
scheduler) that the reference delegated to the external KubeDevice repo.

Layer map (mirrors SURVEY.md §1):

- ``kubetpu.api``         — re-creation of the KubeDevice-API contract
                            (types, resource translation, logging, plugin
                            interfaces) the reference compiles against.
- ``kubetpu.plugintypes`` — shared data model: resource name constants,
                            sorted topology trees, and the new ICI torus
                            mesh model for TPU slices.
- ``kubetpu.tpuinfo``     — C++ hardware probe behind an exec-JSON boundary
                            (analog of nvmlinfo, reference
                            nvidiagpuplugin/nvmlinfo/main.go).
- ``kubetpu.device``      — node-agent device managers (TPU and NVIDIA)
                            implementing ``api.device.Device``.
- ``kubetpu.scheduler``   — topology-aware scheduler plugins implementing
                            ``api.devicescheduler.DeviceScheduler``.
- ``kubetpu.core``        — stand-in for the KubeDevice core: scheduler
                            loop, group (gang) scheduler, AllocateFrom fill.
- ``kubetpu.jobs``        — JAX integration: turn a chip allocation into a
                            ``jax.sharding.Mesh`` and run sharded training.
"""

__version__ = "0.1.0"
