"""ICI torus mesh model: chip coordinates, slice shapes, contiguity scoring.

This is the TPU build's replacement for the reference's NVLink P2P link-level
model (``nvidia_gpu_manager.go:157-180``): where KubeGPU encodes "things that
communicate fast" as a 2-level tree keyed by P2P link type, a TPU slice is a
2D/3D torus of chips joined by ICI links, and locality is *geometric* —
a 2x2 block and a 1x4 line both group 4 chips but have different bisection
bandwidth, which a tree cannot express (SURVEY.md §7 "hard parts").

The model:

- A slice topology (e.g. ``v5e-8``) is a mesh shape (2, 4) with per-dimension
  wraparound flags, tiled by hosts in ``host_shape`` blocks.
- An *allocation* is a set of chip coordinates; its ICI-contiguity score is
  the number of ICI links internal to the set divided by the maximum internal
  links any equally-sized ideal rectangular block achieves (1.0 = perfectly
  contiguous rectangle, approaching 0 = scattered chips).
- ``find_contiguous_block`` enumerates rectangular sub-slices (all
  factorizations x all torus placements) to place an n-chip gang on the best
  available block — the geometric generalization of the reference's greedy
  tree walk (``gpu.go:247-271``).
"""

from __future__ import annotations

import functools
import itertools
from dataclasses import dataclass
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

Coord = Tuple[int, ...]


@dataclass(frozen=True)
class TpuTopology:
    """A TPU slice topology.

    ``mesh_shape`` — chips per torus dimension (2D for v5e/v5p-small, 3D for
    v4-style slices). ``wrap`` — whether ICI wraparound links exist per
    dimension (full-torus dimensions wrap). ``host_shape`` — the block of
    chips owned by one host; hosts tile the mesh in row-major blocks.
    """

    name: str
    generation: str
    mesh_shape: Tuple[int, ...]
    wrap: Tuple[bool, ...]
    host_shape: Tuple[int, ...]
    hbm_bytes_per_chip: int = 16 * 1024**3  # v5e: 16 GiB HBM per chip

    @property
    def num_chips(self) -> int:
        n = 1
        for d in self.mesh_shape:
            n *= d
        return n

    @property
    def chips_per_host(self) -> int:
        n = 1
        for d in self.host_shape:
            n *= d
        return n

    @property
    def num_hosts(self) -> int:
        return self.num_chips // self.chips_per_host

    def coords(self) -> List[Coord]:
        """All chip coordinates in row-major order."""
        return [c for c in itertools.product(*(range(d) for d in self.mesh_shape))]

    def chip_index(self, coord: Coord) -> int:
        """Row-major linear index of a coordinate."""
        idx = 0
        for c, d in zip(coord, self.mesh_shape):
            idx = idx * d + c
        return idx

    def index_coord(self, index: int) -> Coord:
        out: List[int] = []
        for d in reversed(self.mesh_shape):
            out.append(index % d)
            index //= d
        return tuple(reversed(out))

    def host_of(self, coord: Coord) -> int:
        """Host index owning a chip: hosts tile the mesh in row-major
        ``host_shape`` blocks (v5e-64 = 8x8 chips = 8 hosts of 2x4)."""
        hosts_per_dim = [m // h for m, h in zip(self.mesh_shape, self.host_shape)]
        idx = 0
        for c, h, n in zip(coord, self.host_shape, hosts_per_dim):
            idx = idx * n + (c // h)
        return idx

    def host_coords(self, host: int) -> List[Coord]:
        """Chip coordinates belonging to a host block."""
        hosts_per_dim = [m // h for m, h in zip(self.mesh_shape, self.host_shape)]
        block: List[int] = []
        for n in reversed(hosts_per_dim):
            block.append(host % n)
            host //= n
        block.reverse()
        origin = [b * h for b, h in zip(block, self.host_shape)]
        ranges = [range(o, o + h) for o, h in zip(origin, self.host_shape)]
        return [c for c in itertools.product(*ranges)]

    def neighbors(self, coord: Coord) -> Tuple[Coord, ...]:
        """ICI neighbors of a chip (±1 per dimension, wrapping where the
        torus wraps). Cached — pure in (topology, coord) and called per
        chip inside the scheduling hot path's contiguity scoring."""
        return _neighbors_cached(self, coord)


@functools.lru_cache(maxsize=65536)
def _neighbors_cached(topo: "TpuTopology", coord: Coord) -> Tuple[Coord, ...]:
    out: List[Coord] = []
    for dim, (c, d, w) in enumerate(zip(coord, topo.mesh_shape, topo.wrap)):
        for delta in (-1, 1):
            nc = c + delta
            if w:
                nc %= d
            elif nc < 0 or nc >= d:
                continue
            if d == 1:
                continue
            n = list(coord)
            n[dim] = nc
            out.append(tuple(n))
    return tuple(out)


def _mk(name: str, gen: str, shape: Tuple[int, ...], host: Tuple[int, ...],
        wrap: Optional[Tuple[bool, ...]] = None, hbm: int = 16 * 1024**3) -> TpuTopology:
    if wrap is None:
        # Wraparound links exist on dimensions that span the full torus
        # (v5e wraps at 16; 3D v4-style slices wrap on dims >= 4).
        wrap = tuple((d >= 16) if len(shape) == 2 else (d >= 4) for d in shape)
    return TpuTopology(name=name, generation=gen, mesh_shape=shape, wrap=wrap,
                       host_shape=host, hbm_bytes_per_chip=hbm)


# Registry of known slice topologies. v5e shapes follow the SURVEY.md §7
# model: one v5e host owns a 2x4 block of 8 chips; v5e-64 = 8 hosts on an
# 8x8 mesh; v5e-256 = a full 16x16 torus pod.
TOPOLOGIES: Dict[str, TpuTopology] = {
    t.name: t
    for t in [
        _mk("v5e-1", "v5e", (1, 1), (1, 1)),
        _mk("v5e-4", "v5e", (2, 2), (2, 2)),
        _mk("v5e-8", "v5e", (2, 4), (2, 4)),
        _mk("v5e-16", "v5e", (4, 4), (2, 4)),
        _mk("v5e-32", "v5e", (4, 8), (2, 4)),
        _mk("v5e-64", "v5e", (8, 8), (2, 4)),
        _mk("v5e-128", "v5e", (8, 16), (2, 4)),
        _mk("v5e-256", "v5e", (16, 16), (2, 4)),
        _mk("v4-8", "v4", (2, 2, 2), (2, 2, 1), hbm=32 * 1024**3),
        _mk("v4-16", "v4", (2, 2, 4), (2, 2, 1), hbm=32 * 1024**3),
        _mk("v4-32", "v4", (2, 2, 8), (2, 2, 1), hbm=32 * 1024**3),
        _mk("v4-64", "v4", (4, 4, 4), (2, 2, 1), hbm=32 * 1024**3),
        _mk("v5p-8", "v5p", (2, 2, 2), (2, 2, 1), hbm=95 * 1024**3),
    ]
}


def internal_links(coords: Iterable[Coord], topo: TpuTopology) -> int:
    """Number of ICI links with both endpoints inside *coords*."""
    cset = set(coords)
    links = 0
    for c in cset:
        for n in topo.neighbors(c):
            if n in cset:
                links += 1
    return links // 2  # each link counted from both endpoints


@functools.lru_cache(maxsize=4096)
def factorizations(n: int, ndims: int) -> List[Tuple[int, ...]]:
    """All dimension tuples with product *n*, most compact (near-square/cube)
    first — compactness = smaller sum of dims = more internal ICI links."""
    shapes: Set[Tuple[int, ...]] = set()

    def rec(remaining: int, dims: Tuple[int, ...]) -> None:
        if len(dims) == ndims - 1:
            shapes.add(dims + (remaining,))
            return
        d = 1
        while d <= remaining:
            if remaining % d == 0:
                rec(remaining // d, dims + (d,))
            d += 1

    rec(n, ())
    return sorted(shapes, key=lambda s: (sum(s), s))


def _fill_cells(n: int, fill_axis: int, cross: Sequence[int], ndims: int) -> List[Coord]:
    """First *n* coordinates of a slab: full cross-sections of shape *cross*
    stacked along *fill_axis* (the most-compact achievable packing of a
    non-rectangular count)."""
    cross_axes = [a for a in range(ndims) if a != fill_axis]
    cells: List[Coord] = []
    layer = 0
    while len(cells) < n:
        for rest in itertools.product(*(range(c) for c in cross)):
            coord = [0] * ndims
            coord[fill_axis] = layer
            for a, v in zip(cross_axes, rest):
                coord[a] = v
            cells.append(tuple(coord))
            if len(cells) == n:
                break
        layer += 1
    return cells


@functools.lru_cache(maxsize=4096)
def max_internal_links(n: int, topo: TpuTopology) -> int:
    """Best internal link count achievable by n chips in this topology —
    the denominator of the contiguity score. Pure in (n, topo) and on the
    scheduling hot path, hence cached.

    Enumerates achievable compact packings (full cross-section slabs stacked
    along each axis, the last slab possibly partial) anchored at the origin
    and counts their real links, so the ideal is always attainable on this
    mesh — a pure formula (e.g. the 2n - 2*sqrt(n) polyomino bound) can be
    unattainable on narrow meshes and would make perfect allocations score
    below 1.0.
    """
    if n <= 1:
        return 0
    ndims = len(topo.mesh_shape)
    best = 0
    for fill_axis in range(ndims):
        cross_limits = [topo.mesh_shape[a] for a in range(ndims) if a != fill_axis]
        for cross in itertools.product(*(range(1, c + 1) for c in cross_limits)):
            cross_n = 1
            for c in cross:
                cross_n *= c
            layers = -(-n // cross_n)  # ceil
            if layers > topo.mesh_shape[fill_axis]:
                continue
            cells = _fill_cells(n, fill_axis, cross, ndims)
            best = max(best, internal_links(cells, topo))
    if best == 0:
        best = n - 1  # degenerate mesh smaller than n: treat a line as ideal
    return best


def contiguity_score(coords: Iterable[Coord], topo: TpuTopology) -> float:
    """ICI-contiguity in [0, 1]: internal links / ideal-block links.
    1.0 for a perfect rectangular sub-slice; single chips score 1.0."""
    cset = set(coords)
    n = len(cset)
    if n <= 1:
        return 1.0
    ideal = max_internal_links(n, topo)
    if ideal == 0:
        return 1.0
    return min(1.0, internal_links(cset, topo) / float(ideal))


def enumerate_blocks(topo: TpuTopology, shape: Sequence[int]) -> List[List[Coord]]:
    """All placements of a rectangular block of *shape* on the torus
    (origins slide with wraparound only on wrapping dimensions)."""
    origins_per_dim: List[range] = []
    for d, m, w in zip(shape, topo.mesh_shape, topo.wrap):
        if d > m:
            return []
        origins_per_dim.append(range(m) if (w and d < m) else range(m - d + 1))
    out: List[List[Coord]] = []
    for origin in itertools.product(*origins_per_dim):
        block = [
            tuple((o + off) % m for o, off, m in zip(origin, offsets, topo.mesh_shape))
            for offsets in itertools.product(*(range(d) for d in shape))
        ]
        out.append(block)
    return out


@functools.lru_cache(maxsize=4096)
def _rect_offsets(shape: Tuple[int, ...]) -> Tuple[Coord, ...]:
    return tuple(itertools.product(*(range(d) for d in shape)))


@functools.lru_cache(maxsize=1024)
def host_block_links(topo: "TpuTopology", host_grid_shape: Tuple[int, ...]) -> int:
    """Internal chip-level ICI links of the rectangular chip region covered
    by a host-grid block of *host_grid_shape*. Hosts own anisotropic chip
    blocks (v5e: 2x4), so host-grid compactness != chip compactness — gang
    host selection ranks candidate host rectangles by THIS. Pure geometry,
    cached per (topology, shape): it sits on the gang-scheduling hot path."""
    region = [
        tuple(c)
        for c in itertools.product(
            *(range(s * h) for s, h in zip(host_grid_shape, topo.host_shape))
        )
    ]
    return internal_links(region, topo)


def _place_rect(
    free: Set[Coord], shape: Sequence[int], topo: TpuTopology
) -> Optional[List[Coord]]:
    """First free placement of a rectangular block (origins slide with
    wraparound only on wrapping dimensions). Early-aborts per candidate on
    the first non-free cell — this is the schedule-latency hot path."""
    if any(d > m for d, m in zip(shape, topo.mesh_shape)):
        return None
    offsets = _rect_offsets(tuple(shape))
    mesh = topo.mesh_shape
    wrap = topo.wrap
    # Every placement's anchor (the all-zero-offset cell) is itself free, so
    # candidate origins are the free cells — |free| candidates instead of a
    # full torus sweep (free is per-host-sized in the predicate loop; the
    # sweep dominated 500-node p50 before this).
    for origin in sorted(free):
        if any(
            not w and o + d > m
            for o, d, m, w in zip(origin, shape, mesh, wrap)
        ):
            continue  # would fall off a non-wrapping edge
        block: List[Coord] = []
        ok = True
        for off in offsets:
            cell = tuple((o + f) % m for o, f, m in zip(origin, off, mesh))
            if cell not in free:
                ok = False
                break
            block.append(cell)
        if ok:
            return block
    return None


def find_perfect_block(
    free: Set[Coord], n: int, topo: TpuTopology
) -> Optional[List[Coord]]:
    """A contiguity-1.0 rectangular n-chip block within *free*, or None —
    unlike ``find_contiguous_block`` this never falls back to a fragmented
    set, so it answers "is a contiguity-1.0 placement possible?" (the
    defragmentation criterion). Only shapes whose internal links reach
    ``max_internal_links`` qualify: a 1x4 line is an exact rectangle but
    scores 0.75 where a 2x2 fits, and calling it perfect would both let
    defrag declare victory early and make this function disagree with the
    score it claims to certify."""
    if n <= 0:
        return []
    if len(free) < n:
        return None
    ideal = max_internal_links(n, topo)
    for shape in factorizations(n, len(topo.mesh_shape)):
        # links are translation-invariant on the torus: evaluate the shape
        # anchored at the origin (cached via lru on host_block_links-style
        # reuse is unnecessary; factorization lists are tiny)
        cells = [tuple(c) for c in itertools.product(*(range(d) for d in shape))]
        if internal_links(cells, topo) != ideal:
            continue
        block = _place_rect(free, shape, topo)
        if block is not None:
            return sorted(block)
    return None


def find_contiguous_block(
    free: Set[Coord], n: int, topo: TpuTopology
) -> Optional[Tuple[List[Coord], float]]:
    """Place an n-chip gang on the best free block: try rectangular shapes
    most-compact-first; fall back to greedy compact growth when no exact
    rectangle is free. Returns (sorted coords, contiguity score) or None if
    fewer than n chips are free."""
    if n <= 0:
        return [], 1.0
    if len(free) < n:
        return None
    block = find_perfect_block(free, n, topo)
    if block is not None:
        return block, contiguity_score(block, topo)
    # No exact rectangle free: greedy frontier growth from each free chip,
    # preferring candidates with the most already-chosen neighbors.
    best: Optional[List[Coord]] = None
    best_score = -1.0
    for seed in sorted(free):
        chosen: Set[Coord] = {seed}
        while len(chosen) < n:
            frontier: Dict[Coord, int] = {}
            for c in chosen:
                for nb in topo.neighbors(c):
                    if nb in free and nb not in chosen:
                        frontier[nb] = frontier.get(nb, 0) + 1
            if not frontier:
                # disconnected region — take nearest remaining free chips
                remaining = sorted(free - chosen)
                chosen.update(remaining[: n - len(chosen)])
                break
            pick = max(sorted(frontier), key=lambda c: frontier[c])
            chosen.add(pick)
        if len(chosen) == n:
            s = contiguity_score(chosen, topo)
            if s > best_score:
                best, best_score = sorted(chosen), s
    if best is None:
        return None
    return best, best_score


def slice_score(topo: TpuTopology, free: FrozenSet[Coord]) -> float:
    """A node-level desirability score for tree tie-breaking: how contiguous
    the node's free chips are (denser/more-connected free space ranks
    higher, the ICI analog of the reference's depth/density tree score,
    ``gpu.go:180-190``)."""
    if not free:
        return 0.0
    return contiguity_score(free, topo) * len(free)
