"""Shared plugin data model (analog of reference ``gpuplugintypes``).

Resource-name constants (reference ``gpuplugintypes/types.go:5-7`` defines
``ResourceGPU = "nvidia.com/gpu"``; ``ResourceTPU`` is the new TPU resource
per BASELINE.json's north star), the canonical topology-tree node
(``types.go:9-13``), tree utilities (``typeutils.go``), and the ICI torus
mesh model that is new in the TPU build (SURVEY.md §7 step 2).
"""

from kubetpu.plugintypes.treetypes import ResourceGPU, ResourceTPU, SortedTreeNode
from kubetpu.plugintypes.treeutils import (
    add_node_to_sorted_tree_node,
    add_to_sorted_tree_node,
    add_to_sorted_tree_node_with_score,
    compare_tree_node,
    format_tree_node,
    log_tree_node,
    print_tree_node,
)

__all__ = [
    "ResourceGPU",
    "ResourceTPU",
    "SortedTreeNode",
    "add_node_to_sorted_tree_node",
    "add_to_sorted_tree_node",
    "add_to_sorted_tree_node_with_score",
    "compare_tree_node",
    "format_tree_node",
    "log_tree_node",
    "print_tree_node",
]
