"""Sorted-tree utilities: ordered insertion, structural compare, print/log.

Reference: ``gpuplugintypes/typeutils.go`` — ordered insertion keeping
children in descending (Val, Score) order (``:10-40``), recursive structural
equality (``:75-93``), print/log helpers (``:42-72``).
"""

from __future__ import annotations

from typing import List, Optional

from kubetpu.api import utils
from kubetpu.plugintypes.treetypes import SortedTreeNode


def _insertion_point(node: SortedTreeNode, val: int, score: float) -> int:
    """First index whose child sorts strictly below (val, score); children
    stay in descending order (reference findNodeInsertionPoint,
    typeutils.go:10-23)."""
    for index, child in enumerate(node.children):
        if child.val < val or (child.val == val and child.score < score):
            return index
    return len(node.children)


def add_to_sorted_tree_node_with_score(
    node: SortedTreeNode, val: int, score: float
) -> SortedTreeNode:
    """Insert a new child with (val, score); returns the new child
    (reference AddToSortedTreeNodeWithScore, typeutils.go:27-31)."""
    child = SortedTreeNode(val=val, score=score)
    node.children.insert(_insertion_point(node, val, score), child)
    return child


def add_to_sorted_tree_node(node: SortedTreeNode, val: int) -> SortedTreeNode:
    """Reference AddToSortedTreeNode (typeutils.go:38-40)."""
    return add_to_sorted_tree_node_with_score(node, val, 0.0)


def add_node_to_sorted_tree_node(node: SortedTreeNode, to_add: SortedTreeNode) -> None:
    """Insert an existing subtree as a child in sorted position
    (reference AddNodeToSortedTreeNode, typeutils.go:33-36)."""
    node.children.insert(_insertion_point(node, to_add.val, to_add.score), to_add)


def format_tree_node(node: SortedTreeNode, level: int = 0) -> str:
    """Indented multi-line rendering (reference printTreeNode/logTreeNode,
    typeutils.go:42-65)."""
    lines = ["%s%d" % (" " * (3 * level), node.val)]
    for child in node.children:
        lines.append(format_tree_node(child, level + 1))
    return "\n".join(lines)


def print_tree_node(node: SortedTreeNode) -> None:
    """Reference PrintTreeNode (typeutils.go:52-54)."""
    print(format_tree_node(node))


def log_tree_node(loglevel: int, node: SortedTreeNode) -> None:
    """Gated tree dump (reference LogTreeNode, typeutils.go:66-72)."""
    if utils.logb(loglevel):
        utils.logf(loglevel, "%s", format_tree_node(node))


def compare_tree_node(n1: Optional[SortedTreeNode], n2: Optional[SortedTreeNode]) -> bool:
    """Structural equality on (val, child shape); scores are tie-breakers and
    deliberately not compared (reference CompareTreeNode, typeutils.go:75-93)."""
    if n1 is None and n2 is None:
        return True
    if n1 is None or n2 is None:
        return False
    if n1.val != n2.val:
        return False
    if len(n1.children) != len(n2.children):
        return False
    return all(compare_tree_node(a, b) for a, b in zip(n1.children, n2.children))
