"""Resource constants and the canonical topology-tree node.

Reference: ``gpuplugintypes/types.go:5-13`` — ``ResourceGPU =
"nvidia.com/gpu"`` and ``SortedTreeNode{Val int, Score float64, Child
[]*SortedTreeNode}`` with children kept in descending (Val, Score) order.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

# The TPU scalar resource name (BASELINE.json north star: pod specs request
# "kubedevice/tpu" and schedule onto TPU-VM nodes).
ResourceTPU = "kubedevice/tpu"

# The NVIDIA scalar resource name, kept for heterogeneous clusters
# (reference gpuplugintypes/types.go:6).
ResourceGPU = "nvidia.com/gpu"


@dataclass
class SortedTreeNode:
    """A node in the hierarchical-topology tree.

    ``val`` is the leaf-count (devices) under this node; ``score`` is a
    tie-breaker — in the TPU build it carries the ICI-contiguity score of
    the sub-slice this node represents (generalizing the reference, where it
    carried the subtree's tree-score, ``gpu.go:152``). ``children`` are
    maintained in descending ``(val, score)`` order by the insertion helpers
    in ``treeutils``.
    """

    val: int = 0
    score: float = 0.0
    children: List["SortedTreeNode"] = field(default_factory=list)
