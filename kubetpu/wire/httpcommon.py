"""Shared HTTP plumbing for the wire stack.

Server side (agent + controller): JSON / text replies and the bearer-token
check. One implementation so security hardening (constant-time compare,
latin-1 header handling) can never drift between the two surfaces.

Client side: ``request_json`` / ``request_text`` — THE one urllib call
every wire client routes through (``RemoteDevice``, ``gang_launch``,
``schedsim``, the controller's federation scrapes, the obs CLI), carrying
the chaos-hardening contract in one place (lint rule KTP002 statically
rejects raw ``urlopen`` anywhere else):

- jittered exponential retry with a per-call wall-clock deadline
  (``RetryPolicy``): transient connection failures, timeouts, truncated
  responses and infra-transient 502/503/504 answers are retried;
  application errors (4xx, and plain 500 — deterministic, re-executing
  just repeats it) are surfaced immediately;
- retry SAFETY: GET/DELETE are retried freely (idempotent by contract);
  a POST is retried ONLY when the caller attaches an idempotency key —
  a retried non-keyed POST could double-allocate, so it gets exactly one
  attempt. Keys travel as the ``Idempotency-Key`` header and are deduped
  server-side (``IdempotencyCache``);
- fault injection: an injector installed per-call (``faults=``) or
  process-wide (``faults.install_client``) may drop/delay outbound calls.

``IdempotencyCache`` is the server half of the key contract: a bounded
replay window mapping key -> committed 200 response. Only SUCCESS is
cached — a failed attempt clears the in-flight marker so the retry may
re-execute (at-most-once success, at-least-once attempt).

Observability (Round-8, ``kubetpu.obs``): every ``request_json`` call runs
inside a client trace span with retries as child spans, propagating the
trace context via ``X-Kubetpu-Trace-Id`` / ``X-Kubetpu-Parent-Span``;
``handle_guarded`` adopts it server-side, so controller -> agent chains
stitch into one trace. Client-side wire counters
(``kubetpu_wire_requests_total`` / ``_retried_total``) land on the
process-default ``obs.Registry``.
"""

from __future__ import annotations

import contextlib
import hmac
import http.client
import io
import json
import random as _random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional, Tuple

from kubetpu.obs import registry as obs_registry
from kubetpu.obs import trace as obs_trace

# -- server reply helpers ----------------------------------------------------


def write_json(handler, code: int, obj) -> None:
    body = json.dumps(obj).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    _write_body(handler, body)


def write_text(handler, code: int, text: str,
               content_type: str = "text/plain; version=0.0.4") -> None:
    body = text.encode()
    handler.send_response(code)
    handler.send_header("Content-Type", content_type)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    _write_body(handler, body)


def serve_events_jsonl(handler, render) -> None:
    """The shared ``GET /events`` plumbing (agent, controller and the
    obs exporter all serve the same surface): parse ``?kind=``/
    ``?limit=`` from the handler's path, 400 a non-integer limit, and
    write *render*(kind=, limit=) as NDJSON."""
    import urllib.parse

    url = urllib.parse.urlsplit(handler.path)
    q = urllib.parse.parse_qs(url.query)
    try:
        limit = int(q["limit"][0]) if "limit" in q else None
    except ValueError:
        write_json(handler, 400, {"error": "limit must be an integer"})
        return
    write_text(handler, 200,
               render(kind=(q.get("kind") or [None])[0], limit=limit),
               content_type="application/x-ndjson")


def _write_body(handler, body: bytes) -> None:
    """Body write with the partial-response fault hook: when the fault
    layer marked this request (``_fault_truncate``), advertise the full
    Content-Length but write only half the body and close — the client's
    read raises ``IncompleteRead``, manufacturing the processed-but-
    response-lost window idempotency keys exist for."""
    if getattr(handler, "_fault_truncate", False):
        # consume the mark either way: it must never leak into a later
        # keep-alive request served by the same handler instance
        handler._fault_truncate = False
        if len(body) > 1:
            handler.wfile.write(body[: len(body) // 2])
            handler.close_connection = True
            return
    handler.wfile.write(body)


def check_bearer(headers, token: Optional[str]) -> bool:
    """True when the request may proceed. Constant-time compare — plain ==
    short-circuits at the first differing byte, leaking the secret through
    timing. Compares BYTES: hmac.compare_digest raises TypeError on
    non-ASCII str (http.server hands headers latin-1-decoded), which would
    drop the connection instead of letting the caller reply 401."""
    if token is None:
        return True
    got = headers.get("Authorization", "")
    return hmac.compare_digest(
        got.encode("latin-1", "replace"),
        f"Bearer {token}".encode("latin-1", "replace"),
    )


# -- retrying client ---------------------------------------------------------


@dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff with a retry budget and a per-call
    deadline. ``attempts`` bounds tries; ``deadline`` bounds wall clock
    (whichever is hit first wins — a slow-timeout route must not multiply
    into attempts x timeout)."""

    attempts: int = 4
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5          # fraction of each backoff randomized away
    deadline: float = 30.0       # total wall-clock budget, seconds
    # retry 502/503/504 (infra-transient: injected faults, draining
    # servers, in-flight idempotency dups). A plain 500 is an APPLICATION
    # error — deterministic, so re-executing it just repeats the failure
    # (and its side effects) and delays the surfaced error by the budget.
    retry_5xx: bool = True


DEFAULT_RETRY = RetryPolicy()
NO_RETRY = RetryPolicy(attempts=1)

# transient transport failures worth another attempt;
# http.client.HTTPException covers IncompleteRead (truncated response) and
# BadStatusLine/RemoteDisconnected (connection died mid-exchange)
TRANSIENT_ERRORS = (
    urllib.error.URLError,
    ConnectionError,
    TimeoutError,
    OSError,
    http.client.HTTPException,
)


def request_json(
    url: str,
    payload: Optional[dict] = None,
    *,
    method: Optional[str] = None,
    token: Optional[str] = None,
    timeout: float = 5.0,
    retry: Optional[RetryPolicy] = None,
    idempotency_key: Optional[str] = None,
    headers: Optional[dict] = None,
    faults=None,
) -> dict:
    """One JSON request/response over urllib with the shared retry
    discipline. *method* defaults to GET without a payload, POST with one.
    Raises ``urllib.error.HTTPError`` for a final HTTP error status and
    the last transport exception when every attempt failed.

    Observability (Round-8): the logical call runs inside one trace span
    (child of whatever span the caller holds — a fresh trace root
    otherwise), each retry is a CHILD span tagged with its attempt number,
    and the trace context travels to the server as the
    ``X-Kubetpu-Trace-Id`` / ``X-Kubetpu-Parent-Span`` headers — rebuilt
    per attempt, so a server span parents under the exact attempt that
    reached it. ``kubetpu_wire_requests_total`` / ``_retried_total``
    count on the process-default registry."""
    body = _request_raw(
        url, payload=payload, method=method, token=token, timeout=timeout,
        retry=retry, idempotency_key=idempotency_key, headers=headers,
        faults=faults,
    )
    return json.loads(body)


def request_text(
    url: str,
    *,
    token: Optional[str] = None,
    timeout: float = 5.0,
    retry: Optional[RetryPolicy] = None,
    headers: Optional[dict] = None,
    faults=None,
) -> str:
    """One text GET through the SAME retry/trace/fault machinery as
    ``request_json`` — for the non-JSON wire surfaces (Prometheus
    ``/metrics`` federation scrapes, ``/events`` NDJSON). Before
    Round-12 these were raw ``urlopen`` calls, invisible to fault
    injection and trace stitching; now a scrape rides the one client
    (lint rule KTP002 keeps it that way). Pass ``retry=NO_RETRY`` when
    a miss should stay a gap in a graph instead of a backoff."""
    return _request_raw(
        url, payload=None, method="GET", token=token, timeout=timeout,
        retry=retry, idempotency_key=None, headers=headers, faults=faults,
    ).decode()


def _request_raw(
    url: str,
    payload: Optional[dict],
    *,
    method: Optional[str],
    token: Optional[str],
    timeout: float,
    retry: Optional[RetryPolicy],
    idempotency_key: Optional[str],
    headers: Optional[dict],
    faults,
) -> bytes:
    """The shared client workhorse: retry loop, idempotency gating,
    trace spans + header propagation, fault injection, wire counters.
    Returns the response body bytes; the public wrappers decide how to
    decode them."""
    from kubetpu.wire import faults as faults_mod

    reg = obs_registry.default_registry()
    retry = retry or DEFAULT_RETRY
    method = method or ("GET" if payload is None else "POST")
    data = None if payload is None else json.dumps(payload).encode()
    hdrs = {"Content-Type": "application/json"}
    if token:
        hdrs["Authorization"] = f"Bearer {token}"
    if idempotency_key:
        hdrs["Idempotency-Key"] = idempotency_key
    if headers:
        hdrs.update(headers)
    # retry safety: GET/DELETE are idempotent by wire contract; a POST is
    # only retried under an idempotency key (the server dedups replays)
    retriable = method in ("GET", "HEAD", "DELETE") or bool(idempotency_key)
    attempts = retry.attempts if retriable else 1
    deadline = time.monotonic() + retry.deadline
    delay = retry.base_delay
    # route policies are registered by PATH prefix ("/allocate"), matching
    # the server side — hand the injector the path, not the full URL
    fault_path = urllib.parse.urlsplit(url).path or "/"
    last_exc: Optional[BaseException] = None
    reg.counter("kubetpu_wire_requests_total").inc()
    with obs_trace.span(f"http.{method}", component="wire-client",
                        path=fault_path):
        for attempt in range(attempts):
            injector = (faults if faults is not None
                        else faults_mod.client_injector())
            if attempt:
                reg.counter("kubetpu_wire_requests_retried_total").inc()
                attempt_cm = obs_trace.span(
                    "http.retry", component="wire-client",
                    path=fault_path, attempt=attempt)
            else:
                attempt_cm = contextlib.nullcontext()
            try:
                with attempt_cm:
                    if injector is not None:
                        injector.client_fault(fault_path)
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    # context rebuilt per attempt: inside a retry span the
                    # propagated parent IS that retry span
                    attempt_hdrs = dict(hdrs)
                    attempt_hdrs.update(obs_trace.wire_headers())
                    req = urllib.request.Request(
                        url, data=data, headers=attempt_hdrs, method=method
                    )
                    with urllib.request.urlopen(
                        req, timeout=min(timeout, remaining)
                    ) as resp:
                        return resp.read()
            except urllib.error.HTTPError as e:
                if not (retry.retry_5xx and e.code in (502, 503, 504)
                        and retriable) or attempt + 1 >= attempts:
                    raise
                # drain the socket but keep the body READABLE: the deadline
                # may end the loop and re-raise this error, and callers read
                # the server's error detail from it. Reassigning e.fp is NOT
                # enough (addinfourl delegates read() to the original file),
                # so rebuild the error around a buffered body.
                try:
                    last_exc = urllib.error.HTTPError(
                        e.url, e.code, e.reason, e.headers,
                        io.BytesIO(e.read())
                    )
                except Exception:  # noqa: BLE001 — body already gone
                    last_exc = e
                    e.close()
            except TRANSIENT_ERRORS as e:
                last_exc = e
            if attempt + 1 >= attempts:
                break
            sleep = min(delay, retry.max_delay,
                        max(0.0, deadline - time.monotonic()))
            if sleep > 0:
                time.sleep(sleep * (1.0 - retry.jitter * _random.random()))
            delay *= retry.multiplier
        if last_exc is None:
            last_exc = TimeoutError(
                f"{method} {url}: retry deadline ({retry.deadline}s) exhausted"
            )
        raise last_exc


# -- idempotency (server side) -----------------------------------------------


class IdempotencyCache:
    """Bounded dedup window for idempotency-keyed requests.

    ``begin(key)`` -> ("new", None) | ("inflight", None) |
    ("replay", (code, obj)). The caller runs the real work only on "new",
    then ``commit(key, code, obj)`` on success or ``abort(key)`` on
    failure (so a retry after a FAILED attempt re-executes instead of
    replaying the failure). "inflight" means the original attempt is still
    executing — the server answers 503 and the client's backoff lands the
    retry after commit/abort. Entries expire after ``ttl`` seconds and the
    window holds at most ``capacity`` committed responses (FIFO)."""

    _INFLIGHT = object()

    def __init__(self, capacity: int = 1024, ttl: float = 300.0) -> None:
        self.capacity = capacity
        self.ttl = ttl
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, Tuple[object, float]]" = OrderedDict()

    def begin(self, key: str) -> Tuple[str, Optional[tuple]]:
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                value, ts = entry
                if value is not self._INFLIGHT and now - ts > self.ttl:
                    del self._entries[key]
                elif value is self._INFLIGHT:
                    return "inflight", None
                else:
                    return "replay", value  # (code, obj)
            self._entries[key] = (self._INFLIGHT, now)
            return "new", None

    def commit(self, key: str, code: int, obj) -> None:
        with self._lock:
            self._entries[key] = ((code, obj), time.monotonic())
            if len(self._entries) > self.capacity:
                # trim oldest COMMITTED entries only: evicting an INFLIGHT
                # marker would let that key's retry re-execute concurrently
                # with its original — the double-execution this cache
                # exists to prevent
                for k in list(self._entries):
                    if len(self._entries) <= self.capacity:
                        break
                    if self._entries[k][0] is not self._INFLIGHT:
                        del self._entries[k]

    def abort(self, key: str) -> None:
        with self._lock:
            self._entries.pop(key, None)


def run_idempotent(handler, cache: IdempotencyCache, key: Optional[str],
                   fn, on_replay=None) -> None:
    """Execute ``fn() -> (code, obj)`` under the Idempotency-Key contract
    and write the JSON reply — THE one implementation of the dance, shared
    by the agent's /allocate and the controller's /pods so the semantics
    can never drift: committed keys replay (``on_replay`` hook for
    counters), a key whose original attempt is still executing answers 503
    (retryable — the client's backoff lands after commit/abort), success
    commits, anything else aborts so a retry re-executes. Exceptions
    propagate to the caller's error mapping after the abort."""
    if not key:
        write_json(handler, *fn())
        return
    state, stored = cache.begin(key)
    if state == "replay":
        if on_replay is not None:
            on_replay()
        write_json(handler, *stored)
        return
    if state == "inflight":
        write_json(handler, 503,
                   {"error": "idempotent request still in flight"})
        return
    try:
        code, obj = fn()
    except BaseException:
        cache.abort(key)
        raise
    if code == 200:
        cache.commit(key, code, obj)
    else:
        cache.abort(key)
    write_json(handler, code, obj)


class _InflightBracket:
    __slots__ = ("_tracker",)

    def __init__(self, tracker: "InflightTracker") -> None:
        self._tracker = tracker

    def __enter__(self):
        with self._tracker._cv:
            self._tracker._n += 1

    def __exit__(self, *exc):
        with self._tracker._cv:
            self._tracker._n -= 1
            self._tracker._cv.notify_all()


class InflightTracker:
    """Counts in-flight HTTP requests so a graceful shutdown can wait for
    them — shared by both wire servers (one implementation, zero drift)."""

    def __init__(self) -> None:
        self._n = 0
        self._cv = threading.Condition()

    def track(self) -> _InflightBracket:
        """Context manager bracketing one request."""
        return _InflightBracket(self)

    def wait_idle(self, timeout: float) -> bool:
        """Block (bounded) until no request is in flight."""
        with self._cv:
            return self._cv.wait_for(lambda: self._n == 0, timeout=timeout)


def handle_guarded(server, handler, dispatch) -> None:
    """THE per-request bracket both wire servers wrap every HTTP verb in:
    count the request in flight (so graceful shutdown can wait), adopt the
    caller's trace context (``X-Kubetpu-Trace-Id`` headers) and open a
    server span, consult the server's fault injector (chaos
    drop/delay/error/partial), then run *dispatch*. Lives here so the
    order (track -> trace -> faults -> route) can never drift between the
    agent and the controller. *server* needs ``._inflight``
    (InflightTracker) and ``.faults`` attributes; an ``.obs_component``
    string names the server in span records."""
    comp = getattr(server, "obs_component", type(server).__name__)
    with server._inflight.track():
        with obs_trace.attach_wire_context(handler.headers):
            with obs_trace.span(
                f"{handler.command} {handler.path}", component=comp
            ) as sp:
                if server.faults is not None and server.faults.server_fault(
                        handler):
                    # drop/error consumed the request before routing —
                    # visible in the trace as a server span that did no work
                    sp.tag(fault="injected")
                    return
                dispatch()
