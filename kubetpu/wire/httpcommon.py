"""Shared HTTP plumbing for the wire servers (agent + controller): JSON /
text replies and the bearer-token check. One implementation so security
hardening (constant-time compare, latin-1 header handling) can never drift
between the two surfaces."""

from __future__ import annotations

import hmac
import json
from typing import Optional


def write_json(handler, code: int, obj) -> None:
    body = json.dumps(obj).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def write_text(handler, code: int, text: str,
               content_type: str = "text/plain; version=0.0.4") -> None:
    body = text.encode()
    handler.send_response(code)
    handler.send_header("Content-Type", content_type)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def check_bearer(headers, token: Optional[str]) -> bool:
    """True when the request may proceed. Constant-time compare — plain ==
    short-circuits at the first differing byte, leaking the secret through
    timing. Compares BYTES: hmac.compare_digest raises TypeError on
    non-ASCII str (http.server hands headers latin-1-decoded), which would
    drop the connection instead of letting the caller reply 401."""
    if token is None:
        return True
    got = headers.get("Authorization", "")
    return hmac.compare_digest(
        got.encode("latin-1", "replace"),
        f"Bearer {token}".encode("latin-1", "replace"),
    )
