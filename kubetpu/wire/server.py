"""``NodeAgentServer`` — the node agent's HTTP surface over a local device
manager.

This is the transport leg the reference leaves to the external KubeDevice
core (its CRI shim and scheduler are separate processes; VERDICT r1 #1): a
small threaded HTTP server wrapping a ``device.Device``:

    GET  /healthz   -> {"ok": true, "node": <name>, "plugin": <device name>,
                        "draining": <bool>}
    GET  /nodeinfo  -> NodeInfo JSON (fresh advertisement; the manager's
                       probe cache bounds actual hardware queries)
    GET  /metrics   -> Prometheus text rendered from the agent's
                       ``obs.Registry``: request/error counters, advertised
                       capacity gauges, uptime (the metrics endpoint the
                       reference never had, SURVEY.md §5.5); the controller
                       scrapes and federates this into its fleet /metrics
    GET  /trace/<id>-> finished spans of one trace from the process tracer
                       (agent legs of a stitched controller trace)
    GET  /events    -> this agent's structured event log (allocate /
                       replay / drain) as JSON Lines, trace-id linked
    POST /allocate  -> {"pod": PodInfo, "container": <name>} ->
                       AllocateResult JSON (the container-start injection
                       step, run node-local where the devices live)

Robustness (Round-7):

- idempotent allocate: a request carrying an ``Idempotency-Key`` header is
  deduped through a bounded replay window — a client retry whose first
  response was lost gets the committed result replayed (counted as
  ``allocate_replays``), never a second device allocation;
- graceful drain/shutdown: ``drain()`` stops accepting mutating work
  (POST -> 503, liveness keeps answering with ``"draining": true``);
  ``shutdown(graceful=True)`` drains, waits for in-flight requests to
  finish (bounded), then stops the listener — no request is cut mid-write;
- fault injection: pass ``faults=FaultInjector(...)`` to chaos-test the
  surface (seeded drop/delay/5xx/partial per route, ``wire.faults``).

Stdlib-only (http.server), threaded so a slow probe doesn't block health
checks. Binds 127.0.0.1 by default; port 0 picks an ephemeral port — the
bound address is printed/returned so spawners can discover it.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kubetpu.api import utils
from kubetpu.api.device import Device
from kubetpu.api.types import new_node_info
from kubetpu.obs import trace as obs_trace
from kubetpu.obs.events import EventLog
from kubetpu.obs.registry import Registry, install_process_gauges
from kubetpu.wire.codec import (
    allocate_result_to_json,
    node_info_to_json,
    pod_info_from_json,
)
from kubetpu.wire.httpcommon import (
    IdempotencyCache,
    InflightTracker,
    check_bearer,
    handle_guarded,
    run_idempotent,
    serve_events_jsonl,
    write_json,
    write_text,
)


class NodeAgentServer:
    """Serve one node's device manager to the control plane."""

    def __init__(
        self,
        device: Device,
        node_name: str,
        host: str = "127.0.0.1",
        port: int = 0,
        token: "str | None" = None,
        faults=None,
        idem_window: float = 300.0,
    ) -> None:
        """*token*: shared-secret auth — when set, every request must carry
        ``Authorization: Bearer <token>`` or is rejected 401 (``/healthz``
        stays open for liveness probes). Matches ``RemoteDevice(token=)``;
        the agent CLI reads it from ``KUBETPU_WIRE_TOKEN``.
        *faults*: optional ``FaultInjector`` for chaos testing.
        *idem_window*: seconds an allocate's committed response stays
        replayable for idempotency-keyed retries."""
        self.device = device
        self.node_name = node_name
        self.token = token or None  # "" (e.g. a blank env var) = no auth
        self.faults = faults
        self.idem = IdempotencyCache(ttl=idem_window)
        self.started_at = time.time()
        self.obs_component = f"agent:{node_name}"  # names spans from here
        # every counter/gauge lives in ONE thread-safe registry (Round-8);
        # the old hand-rolled counter dict + lock are gone — /metrics
        # renders the registry, writers inc() instruments
        self.registry = Registry()
        install_process_gauges(self.registry, self.obs_component)
        for key in ("nodeinfo_requests", "allocate_requests",
                    "allocate_replays", "releases", "errors"):
            # key ranges over the fixed literal tuple above — KTP004's
            # bounded-f-string proof expands and validates every name
            self.registry.counter(f"kubetpu_agent_{key}_total")
        # legacy alias (pinned by test_wire): the Round-11 standard
        # kubetpu_process_uptime_seconds is the fleet-wide series; this
        # one measures from server construction rather than obs import
        self.registry.gauge_fn(
            "kubetpu_agent_uptime_seconds",
            lambda: time.time() - self.started_at,
        )
        # Round-11: bounded structured event log (allocate/replay/drain),
        # served as JSONL at GET /events, trace-id cross-linked
        self.events = EventLog(component=self.obs_component)
        # graceful lifecycle: while draining, mutating work is refused 503
        # but in-flight requests run to completion (tracked so a graceful
        # shutdown can wait for them)
        self.draining = False
        self._inflight = InflightTracker()
        # last advertised kube capacity — /metrics serves this snapshot
        # instead of re-probing hardware per scrape (a 15s Prometheus
        # interval must not defeat the manager's probe-cache bound). None =
        # never probed (an EMPTY capacity is a valid snapshot).
        self.last_capacity: Optional[dict] = None
        # Round-20 allocation ledger: which pods this agent has handed
        # env/devices to (pod -> container names). Device allocation
        # itself is a stateless env derivation, so this ledger is the
        # agent's ONLY memory of who holds what — the surface a crashed
        # controller re-scrapes (GET /allocations) to diff its replayed
        # journal against, and frees orphans through (POST /release).
        self._alloc_lock = threading.Lock()
        self.allocations: dict = {}
        agent = self

        def bump(key: str) -> None:
            # callers pass literals from the pre-registered set above
            # ktlint: disable=KTP004
            agent.registry.counter(f"kubetpu_agent_{key}_total").inc()

        class Handler(BaseHTTPRequestHandler):
            # quiet the default per-request stderr lines; route to leveled log
            def log_message(self, fmt, *args):  # noqa: N802 (stdlib name)
                utils.logf(5, "agent %s: " + fmt, agent.node_name, *args)

            def _reply(self, code: int, obj: dict) -> None:
                write_json(self, code, obj)

            def _reply_text(self, code: int, text: str) -> None:
                write_text(self, code, text)

            def _authorized(self) -> bool:
                if check_bearer(self.headers, agent.token):
                    return True
                self._reply(401, {"error": "missing or invalid bearer token"})
                return False

            def do_GET(self):  # noqa: N802
                handle_guarded(agent, self, self._do_get)

            def _do_get(self):
                if self.path == "/healthz":
                    self._reply(
                        200,
                        {
                            "ok": True,
                            "node": agent.node_name,
                            "plugin": agent.device.get_name(),
                            "draining": agent.draining,
                        },
                    )
                elif not self._authorized():
                    pass  # 401 already sent
                elif self.path == "/nodeinfo":
                    bump("nodeinfo_requests")
                    try:
                        info = new_node_info(agent.node_name)
                        agent.device.update_node_info(info)
                        agent._capacity_snapshot(info.kube_cap)
                        self._reply(200, node_info_to_json(info))
                    except Exception as e:  # noqa: BLE001 — degrade, stay up
                        bump("errors")
                        self._reply(500, {"error": str(e)})
                elif self.path == "/metrics":
                    if agent.last_capacity is None:
                        # never probed yet: one probe to seed the snapshot
                        try:
                            info = new_node_info(agent.node_name)
                            agent.device.update_node_info(info)
                            agent._capacity_snapshot(info.kube_cap)
                        except Exception:  # noqa: BLE001 — metrics never 500
                            bump("errors")
                    self._reply_text(200, agent.registry.render())
                elif self.path.startswith("/trace/"):
                    tid = self.path[len("/trace/"):]
                    self._reply(200, {
                        "trace": tid,
                        "spans": obs_trace.tracer().spans(tid),
                    })
                elif self.path.split("?")[0] == "/events":
                    serve_events_jsonl(self, agent.events.to_jsonl)
                elif self.path == "/allocations":
                    # the recovery scrape: every pod this agent believes
                    # it allocated for, so a cold-restarted controller
                    # can diff its replayed journal against AGENT truth
                    with agent._alloc_lock:
                        out = {p: sorted(c)
                               for p, c in agent.allocations.items()}
                    self._reply(200, {"node": agent.node_name,
                                      "allocations": out})
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802
                handle_guarded(agent, self, self._do_post)

            def _allocate(self):
                """One allocate execution -> (code, obj); run_idempotent
                commits 200s and aborts the rest (a retried failure
                re-executes). The draining refusal lives HERE, after the
                replay lookup: a keyed retry of an already-committed
                allocate must get its replay even mid-drain (replaying
                mutates nothing; refusing it would leak the committed
                chips when the controller rolls back)."""
                if agent.draining:
                    return 503, {"error": "agent is draining"}
                bump("allocate_requests")
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    pod = pod_info_from_json(req["pod"])
                    cname = req["container"]
                    cont = pod.running_containers.get(
                        cname
                    ) or pod.init_containers.get(cname)
                    if cont is None:
                        return 400, {"error": f"pod has no container {cname!r}"}
                    result = agent.device.allocate(pod, cont)
                    with agent._alloc_lock:
                        agent.allocations.setdefault(pod.name, set()).add(
                            cname)
                    agent.events.emit("allocate", pod=pod.name,
                                      container=cname)
                    return 200, allocate_result_to_json(result)
                except Exception as e:  # noqa: BLE001 — report, stay up
                    bump("errors")
                    return 500, {"error": str(e)}

            def _do_post(self):
                if not self._authorized():  # auth before routing, like GET
                    return
                if self.path == "/release":
                    # forget a pod's ledger entry (controller DELETE
                    # propagation + recovery orphan cleanup). Idempotent
                    # and allowed mid-drain: releasing touches only the
                    # ledger, and an unknown pod is already the goal
                    # state — a retried release must not 404 into a
                    # dead-end for the reconciling controller.
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    name = req.get("pod", "")
                    with agent._alloc_lock:
                        conts = sorted(agent.allocations.pop(name, ()))
                    if conts:
                        bump("releases")
                        agent.events.emit("release", pod=name)
                    self._reply(200, {"released": name,
                                      "containers": conts})
                    return
                if self.path != "/allocate":
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                # idempotency: a keyed retry of an allocate whose response
                # was lost replays the committed result (the shared
                # run_idempotent contract, httpcommon)
                def replayed():
                    bump("allocate_replays")
                    agent.events.emit("allocate_replay")

                run_idempotent(
                    self, agent.idem, self.headers.get("Idempotency-Key"),
                    self._allocate,
                    on_replay=replayed,
                )

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    # -- observability -------------------------------------------------------

    def _capacity_snapshot(self, kube_cap: dict) -> None:
        """Apply a fresh advertisement to the capacity gauges — /metrics
        serves this snapshot instead of re-probing hardware per scrape.
        A resource that stopped being advertised reads 0 (the operator
        sees the loss, the series stays stable for dashboards)."""
        prev = self.last_capacity or {}
        self.last_capacity = dict(kube_cap)
        for res in sorted(set(prev) | set(kube_cap)):
            self.registry.gauge(
                "kubetpu_agent_capacity", resource=res, node=self.node_name
            ).set(kube_cap.get(res, 0))

    @property
    def counters(self) -> dict:
        """Back-compat counter snapshot ({short name: int}) over the
        registry — what the old hand-rolled dict exposed."""
        out = {}
        for name, labels, kind, inst in self.registry.snapshot():
            if (kind == "counter" and not labels
                    and name.startswith("kubetpu_agent_")
                    and name.endswith("_total")):
                out[name[len("kubetpu_agent_"):-len("_total")]] = int(
                    inst.value)
        return out

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        if host in ("0.0.0.0", "::", "::0"):
            # A wildcard bind is listenable but not routable — advertise a
            # reachable name so spawners can paste the URL verbatim.
            import socket

            host = socket.getfqdn()
        return f"http://{host}:{port}"

    def start(self) -> str:
        """Serve in a daemon thread; returns the bound address."""
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="kubetpu-agent", daemon=True
        )
        self._thread.start()
        return self.address

    def serve_forever(self) -> None:
        """Serve on the calling thread (the agent CLI's main loop)."""
        self._httpd.serve_forever()

    def drain(self) -> None:
        """Stop accepting mutating work (POST -> 503); reads and liveness
        keep answering, in-flight requests finish."""
        if not self.draining:
            self.events.emit("drain", node=self.node_name)
        self.draining = True

    def shutdown(self, graceful: bool = True, timeout: float = 5.0) -> None:
        """Stop the server. ``graceful`` first drains and waits (bounded)
        for in-flight requests to complete, so no response is cut mid-write
        — set False to simulate abrupt death (chaos tests)."""
        if graceful:
            self.draining = True
            self._inflight.wait_idle(timeout)
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
