"""``NodeAgentServer`` — the node agent's HTTP surface over a local device
manager.

This is the transport leg the reference leaves to the external KubeDevice
core (its CRI shim and scheduler are separate processes; VERDICT r1 #1): a
small threaded HTTP server wrapping a ``device.Device``:

    GET  /healthz   -> {"ok": true, "node": <name>, "plugin": <device name>}
    GET  /nodeinfo  -> NodeInfo JSON (fresh advertisement; the manager's
                       probe cache bounds actual hardware queries)
    GET  /metrics   -> Prometheus-style text: request/error counters,
                       advertised device count, uptime (the metrics
                       endpoint the reference never had, SURVEY.md §5.5)
    POST /allocate  -> {"pod": PodInfo, "container": <name>} ->
                       AllocateResult JSON (the container-start injection
                       step, run node-local where the devices live)

Stdlib-only (http.server), threaded so a slow probe doesn't block health
checks. Binds 127.0.0.1 by default; port 0 picks an ephemeral port — the
bound address is printed/returned so spawners can discover it.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kubetpu.api import utils
from kubetpu.api.device import Device
from kubetpu.api.types import new_node_info
from kubetpu.wire.codec import (
    allocate_result_to_json,
    node_info_to_json,
    pod_info_from_json,
)
from kubetpu.wire.httpcommon import check_bearer, write_json, write_text


class NodeAgentServer:
    """Serve one node's device manager to the control plane."""

    def __init__(
        self,
        device: Device,
        node_name: str,
        host: str = "127.0.0.1",
        port: int = 0,
        token: "str | None" = None,
    ) -> None:
        """*token*: shared-secret auth — when set, every request must carry
        ``Authorization: Bearer <token>`` or is rejected 401 (``/healthz``
        stays open for liveness probes). Matches ``RemoteDevice(token=)``;
        the agent CLI reads it from ``KUBETPU_WIRE_TOKEN``."""
        self.device = device
        self.node_name = node_name
        self.token = token or None  # "" (e.g. a blank env var) = no auth
        self.started_at = time.time()
        # counters are written under the per-request threads; int += is a
        # single bytecode read-modify-write, so guard with a lock
        self._counter_lock = threading.Lock()
        self.counters = {
            "nodeinfo_requests": 0,
            "allocate_requests": 0,
            "errors": 0,
        }
        # last advertised kube capacity — /metrics serves this snapshot
        # instead of re-probing hardware per scrape (a 15s Prometheus
        # interval must not defeat the manager's probe-cache bound). None =
        # never probed (an EMPTY capacity is a valid snapshot).
        self.last_capacity: Optional[dict] = None
        agent = self

        def bump(key: str) -> None:
            with agent._counter_lock:
                agent.counters[key] += 1

        class Handler(BaseHTTPRequestHandler):
            # quiet the default per-request stderr lines; route to leveled log
            def log_message(self, fmt, *args):  # noqa: N802 (stdlib name)
                utils.logf(5, "agent %s: " + fmt, agent.node_name, *args)

            def _reply(self, code: int, obj: dict) -> None:
                write_json(self, code, obj)

            def _reply_text(self, code: int, text: str) -> None:
                write_text(self, code, text)

            def _authorized(self) -> bool:
                if check_bearer(self.headers, agent.token):
                    return True
                self._reply(401, {"error": "missing or invalid bearer token"})
                return False

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    self._reply(
                        200,
                        {
                            "ok": True,
                            "node": agent.node_name,
                            "plugin": agent.device.get_name(),
                        },
                    )
                elif not self._authorized():
                    pass  # 401 already sent
                elif self.path == "/nodeinfo":
                    bump("nodeinfo_requests")
                    try:
                        info = new_node_info(agent.node_name)
                        agent.device.update_node_info(info)
                        agent.last_capacity = dict(info.kube_cap)
                        self._reply(200, node_info_to_json(info))
                    except Exception as e:  # noqa: BLE001 — degrade, stay up
                        bump("errors")
                        self._reply(500, {"error": str(e)})
                elif self.path == "/metrics":
                    if agent.last_capacity is not None:
                        scalars = dict(sorted(agent.last_capacity.items()))
                    else:  # never probed yet: one probe to seed the snapshot
                        try:
                            info = new_node_info(agent.node_name)
                            agent.device.update_node_info(info)
                            agent.last_capacity = dict(info.kube_cap)
                            scalars = dict(sorted(info.kube_cap.items()))
                        except Exception:  # noqa: BLE001 — metrics never 500
                            bump("errors")
                            scalars = {}
                    with agent._counter_lock:
                        counters = dict(agent.counters)
                    lines = [
                        "# TYPE kubetpu_agent_uptime_seconds gauge",
                        f"kubetpu_agent_uptime_seconds {time.time() - agent.started_at:.1f}",
                    ]
                    for key, val in sorted(counters.items()):
                        lines.append(f"# TYPE kubetpu_agent_{key}_total counter")
                        lines.append(f"kubetpu_agent_{key}_total {val}")
                    for res, val in scalars.items():
                        lines.append(
                            'kubetpu_agent_capacity{resource="%s",node="%s"} %d'
                            % (res, agent.node_name, val)
                        )
                    self._reply_text(200, "\n".join(lines) + "\n")
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802
                if not self._authorized():  # auth before routing, like GET
                    return
                if self.path != "/allocate":
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                bump("allocate_requests")
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    pod = pod_info_from_json(req["pod"])
                    cname = req["container"]
                    cont = pod.running_containers.get(
                        cname
                    ) or pod.init_containers.get(cname)
                    if cont is None:
                        self._reply(
                            400, {"error": f"pod has no container {cname!r}"}
                        )
                        return
                    result = agent.device.allocate(pod, cont)
                    self._reply(200, allocate_result_to_json(result))
                except Exception as e:  # noqa: BLE001 — report, stay up
                    bump("errors")
                    self._reply(500, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        if host in ("0.0.0.0", "::", "::0"):
            # A wildcard bind is listenable but not routable — advertise a
            # reachable name so spawners can paste the URL verbatim.
            import socket

            host = socket.getfqdn()
        return f"http://{host}:{port}"

    def start(self) -> str:
        """Serve in a daemon thread; returns the bound address."""
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="kubetpu-agent", daemon=True
        )
        self._thread.start()
        return self.address

    def serve_forever(self) -> None:
        """Serve on the calling thread (the agent CLI's main loop)."""
        self._httpd.serve_forever()

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
