"""JSON codecs for the KubeDevice-API types — the agent wire format.

The reference's wire formats are JSON throughout (``nvmlinfo json`` exec
boundary, ``nvgputypes/types.go:45-58``; nvidia-docker REST,
``nvidia_docker_plugin.go:21-27``); these codecs extend the same convention
to the NodeInfo/PodInfo/AllocateResult shapes that cross the agent <->
control-plane boundary. Resource quantities stay integers; resource keys are
the grouped-key grammar strings and round-trip untouched.
"""

from __future__ import annotations

from typing import Dict, List

from kubetpu.api.device import AllocateResult, Mount
from kubetpu.api.types import ContainerInfo, NodeInfo, PodInfo


def node_info_to_json(info: NodeInfo) -> dict:
    return {
        "name": info.name,
        "capacity": dict(info.capacity),
        "allocatable": dict(info.allocatable),
        "kube_cap": dict(info.kube_cap),
        "kube_alloc": dict(info.kube_alloc),
    }


def node_info_from_json(obj: dict) -> NodeInfo:
    return NodeInfo(
        name=obj.get("name", ""),
        capacity=dict(obj.get("capacity", {})),
        allocatable=dict(obj.get("allocatable", {})),
        kube_cap=dict(obj.get("kube_cap", {})),
        kube_alloc=dict(obj.get("kube_alloc", {})),
    )


def _container_to_json(cont: ContainerInfo) -> dict:
    return {
        "requests": dict(cont.requests),
        "kube_requests": dict(cont.kube_requests),
        "dev_requests": dict(cont.dev_requests),
        "allocate_from": dict(cont.allocate_from),
    }


def _container_from_json(obj: dict) -> ContainerInfo:
    return ContainerInfo(
        requests=dict(obj.get("requests", {})),
        kube_requests=dict(obj.get("kube_requests", {})),
        dev_requests=dict(obj.get("dev_requests", {})),
        allocate_from=dict(obj.get("allocate_from", {})),
    )


def pod_info_to_json(pod: PodInfo) -> dict:
    return {
        "name": pod.name,
        "node_name": pod.node_name,
        "requests": dict(pod.requests),
        "init_containers": {
            k: _container_to_json(v) for k, v in pod.init_containers.items()
        },
        "running_containers": {
            k: _container_to_json(v) for k, v in pod.running_containers.items()
        },
    }


def pod_info_from_json(obj: dict) -> PodInfo:
    return PodInfo(
        name=obj.get("name", ""),
        node_name=obj.get("node_name", ""),
        requests=dict(obj.get("requests", {})),
        init_containers={
            k: _container_from_json(v)
            for k, v in obj.get("init_containers", {}).items()
        },
        running_containers={
            k: _container_from_json(v)
            for k, v in obj.get("running_containers", {}).items()
        },
    )


def allocate_result_to_json(result: AllocateResult) -> dict:
    mounts, devices, env = result
    return {
        "mounts": [
            {
                "name": m.name,
                "host_path": m.host_path,
                "container_path": m.container_path,
                "read_only": m.read_only,
            }
            for m in mounts
        ],
        "devices": list(devices),
        "env": dict(env),
    }


def allocate_result_from_json(obj: dict) -> AllocateResult:
    mounts: List[Mount] = [
        Mount(
            name=m.get("name", ""),
            host_path=m.get("host_path", ""),
            container_path=m.get("container_path", ""),
            read_only=m.get("read_only", True),
        )
        for m in obj.get("mounts", [])
    ]
    devices: List[str] = list(obj.get("devices", []))
    env: Dict[str, str] = dict(obj.get("env", {}))
    return mounts, devices, env
