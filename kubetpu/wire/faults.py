"""Deterministic fault injection for the wire stack.

The reference inherits Kubernetes' fault tolerance; kubetpu owns its own
control plane, so it must *earn* it — and earned tolerance needs a way to
manufacture the faults it claims to survive. This module is that layer: a
seeded, per-route fault policy installable into both halves of the wire —

- the stdlib HTTP servers (``NodeAgentServer`` / ``ControllerServer`` take
  ``faults=``): each request consults the injector BEFORE routing and may
  be dropped (connection reset, nothing executed), delayed, answered with
  an injected 503 (nothing executed), or answered with a PARTIAL response
  (the handler runs to completion — side effects committed — but the body
  is truncated mid-write, so the client sees an ``IncompleteRead``). The
  partial fault is the important one: it manufactures the
  "processed-but-response-lost" window that makes naive POST retries
  double-allocate, which the idempotency-key dedup must absorb;
- the urllib client path (``RemoteDevice(faults=)`` /
  ``request_json(faults=)``, or process-wide via ``install_client``):
  outbound calls may be dropped (``ConnectionResetError`` before any bytes
  reach the server) or delayed.

Every draw comes from one ``random.Random(seed)`` under a lock, so a chaos
run replays bit-for-bit given the same seed and request order; per-policy
``times`` bounds turn a policy into a deterministic script ("fail the next
call, then behave") for targeted tests.
"""

from __future__ import annotations

import random
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

# fault kinds (the injector's verdict for one request)
OK = "ok"
DROP = "drop"          # reset the connection; the request never executes
DELAY = "delay"        # added latency, then normal handling
ERROR = "error"        # injected 5xx; the request never executes
PARTIAL = "partial"    # request EXECUTES; response body truncated


@dataclass
class RoutePolicy:
    """Per-route fault probabilities. All default to 0 (no injection).

    ``times``: when set, the policy disarms after injecting that many
    faults — a deterministic "fail exactly N calls" script. ``None`` =
    unlimited."""

    drop: float = 0.0
    delay: float = 0.0
    delay_s: float = 0.05
    error: float = 0.0
    error_code: int = 503
    partial: float = 0.0
    times: Optional[int] = None
    injected: int = field(default=0, compare=False)

    def rate(self) -> float:
        return self.drop + self.delay + self.error + self.partial


class FaultInjector:
    """Seeded per-route fault decisions, shared by servers and clients.

    Routes are matched by the LONGEST registered path prefix; the
    ``default`` policy covers everything unmatched. One injector may be
    installed into several servers at once (the chaos soak drives a whole
    controller + N agents off one seed)."""

    def __init__(
        self,
        seed: int = 0,
        default: Optional[RoutePolicy] = None,
        routes: Optional[Dict[str, RoutePolicy]] = None,
    ) -> None:
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.default = default or RoutePolicy()
        self.routes: Dict[str, RoutePolicy] = dict(routes or {})
        self.counts: Dict[str, int] = {}

    # -- policy management ---------------------------------------------------

    def set_route(self, prefix: str, policy: RoutePolicy) -> None:
        with self._lock:
            self.routes[prefix] = policy

    def set_default(self, policy: RoutePolicy) -> None:
        with self._lock:
            self.default = policy

    def clear(self) -> None:
        """Stop injecting (keep counters) — 'the network heals'."""
        with self._lock:
            self.default = RoutePolicy()
            self.routes = {}

    def policy_for(self, path: str) -> RoutePolicy:
        best, best_len = self.default, -1
        for prefix, pol in self.routes.items():
            if path.startswith(prefix) and len(prefix) > best_len:
                best, best_len = pol, len(prefix)
        return best

    # -- decisions -----------------------------------------------------------

    def decide(self, path: str, kinds=None) -> RoutePolicy | tuple:
        """(kind, policy) for one request at *path* — ONE rng draw under
        the lock so concurrent requests replay deterministically given a
        fixed arrival order. *kinds*: the fault kinds the CALLER can
        enact (the client path can only drop/delay); a verdict outside it
        resolves to OK WITHOUT consuming a ``times`` charge or a counter,
        so a scripted server-side fault can't be burned by a client
        call."""
        with self._lock:
            pol = self.policy_for(path)
            if pol.times is not None and pol.injected >= pol.times:
                return OK, pol
            r = self._rng.random()
            for kind, p in ((DROP, pol.drop), (DELAY, pol.delay),
                            (ERROR, pol.error), (PARTIAL, pol.partial)):
                if r < p:
                    if kinds is not None and kind not in kinds:
                        return OK, pol
                    pol.injected += 1
                    self.counts[kind] = self.counts.get(kind, 0) + 1
                    return kind, pol
                r -= p
            return OK, pol

    # -- server installation -------------------------------------------------

    def server_fault(self, handler) -> bool:
        """Consult the injector for one server request. Returns True when
        the request was fully consumed (drop/error) and the handler must
        return WITHOUT executing; False to proceed (possibly after an
        injected delay, possibly with ``handler._fault_truncate`` set so
        the reply writer truncates the body — see httpcommon.write_json)."""
        from kubetpu.wire.httpcommon import write_json

        kind, pol = self.decide(handler.path)
        if kind == DROP:
            # reset without a status line: the client sees the connection
            # die (RemoteDisconnected / ConnectionReset), not an HTTP error
            handler.close_connection = True
            try:
                handler.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            return True
        if kind == DELAY:
            time.sleep(pol.delay_s)
            return False
        if kind == ERROR:
            write_json(handler, pol.error_code,
                       {"error": f"injected fault: {pol.error_code}"})
            return True
        if kind == PARTIAL:
            handler._fault_truncate = True
            return False
        return False

    # -- client installation -------------------------------------------------

    def client_fault(self, path: str) -> None:
        """Consult the injector for one OUTBOUND client call: an injected
        drop raises ``ConnectionResetError`` before any bytes leave (the
        retry layer sees a transient connection failure); a delay sleeps.
        Error/partial are server-side kinds — their charges are left for
        the server to consume (``decide(kinds=...)``)."""
        kind, pol = self.decide(path, kinds=(DROP, DELAY))
        if kind == DROP:
            raise ConnectionResetError(f"injected client drop on {path}")
        if kind == DELAY:
            time.sleep(pol.delay_s)


# -- process-wide client hook (the urllib path) ------------------------------

_client_injector: Optional[FaultInjector] = None


def install_client(injector: Optional[FaultInjector]) -> None:
    """Install *injector* into the shared urllib client path: every
    ``request_json`` call without an explicit ``faults=`` consults it.
    Pass None to uninstall."""
    global _client_injector
    _client_injector = injector


def client_injector() -> Optional[FaultInjector]:
    return _client_injector
