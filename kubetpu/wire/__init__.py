"""The agent <-> control-plane wire boundary.

The reference's architecture is process boundaries: the CRI shim dlopens the
device plugin on every node (``nvidiagpuplugin/cmd/main.go:23``), the
scheduler runs as a separate control-plane process, and hardware probes cross
an exec/HTTP wire (``nvidiagpuplugin/gpu/nvgputypes/types.go:45-58``,
``nvidia_docker_plugin.go:21-27``). The reference itself ships only the
node-local legs and leaves agent<->scheduler transport to the external
KubeDevice core; kubetpu owns the core, so it owns this boundary too:

- ``codec``  — JSON encodings of the KubeDevice-API types (the wire format).
- ``server`` — ``NodeAgentServer``: the node agent's HTTP surface
  (``GET /healthz``, ``GET /nodeinfo``, ``POST /allocate``) over a local
  device manager.
- ``client`` — ``RemoteDevice``: a ``device.Device`` whose probe and
  allocate legs cross the wire, so a ``Cluster`` schedules across live agent
  processes with zero changes to the scheduling path.
- ``httpcommon`` — the shared retrying client (``request_json`` +
  ``RetryPolicy``: jittered exponential backoff, per-call deadlines,
  POST-only-with-key retry safety) and the server-side idempotency
  replay window.
- ``faults`` — deterministic (seeded) per-route fault injection for chaos
  testing: drop/delay/5xx/partial-response, installable into both the
  stdlib servers and the urllib client path.

Observability (Round-8): both servers expose Prometheus ``/metrics``
(the controller's is fleet-federated) and ``/trace/<id>``; the shared
client propagates trace context and records retry spans + wire counters
(``kubetpu.obs``).
"""

from kubetpu.wire.client import AgentUnreachable, RemoteDevice, probe_remote_agent
from kubetpu.wire.codec import (
    allocate_result_from_json,
    allocate_result_to_json,
    node_info_from_json,
    node_info_to_json,
    pod_info_from_json,
    pod_info_to_json,
)
from kubetpu.wire.controller import ControllerServer
from kubetpu.wire.faults import FaultInjector, RoutePolicy
from kubetpu.wire.httpcommon import (
    NO_RETRY,
    IdempotencyCache,
    RetryPolicy,
    request_json,
)
from kubetpu.wire.server import NodeAgentServer

__all__ = [
    "AgentUnreachable",
    "ControllerServer",
    "FaultInjector",
    "IdempotencyCache",
    "NO_RETRY",
    "NodeAgentServer",
    "probe_remote_agent",
    "RemoteDevice",
    "request_json",
    "RetryPolicy",
    "RoutePolicy",
    "allocate_result_from_json",
    "allocate_result_to_json",
    "node_info_from_json",
    "node_info_to_json",
    "pod_info_from_json",
    "pod_info_to_json",
]
