"""The agent <-> control-plane wire boundary.

The reference's architecture is process boundaries: the CRI shim dlopens the
device plugin on every node (``nvidiagpuplugin/cmd/main.go:23``), the
scheduler runs as a separate control-plane process, and hardware probes cross
an exec/HTTP wire (``nvidiagpuplugin/gpu/nvgputypes/types.go:45-58``,
``nvidia_docker_plugin.go:21-27``). The reference itself ships only the
node-local legs and leaves agent<->scheduler transport to the external
KubeDevice core; kubetpu owns the core, so it owns this boundary too:

- ``codec``  — JSON encodings of the KubeDevice-API types (the wire format).
- ``server`` — ``NodeAgentServer``: the node agent's HTTP surface
  (``GET /healthz``, ``GET /nodeinfo``, ``POST /allocate``) over a local
  device manager.
- ``client`` — ``RemoteDevice``: a ``device.Device`` whose probe and
  allocate legs cross the wire, so a ``Cluster`` schedules across live agent
  processes with zero changes to the scheduling path.
"""

from kubetpu.wire.client import AgentUnreachable, RemoteDevice, probe_remote_agent
from kubetpu.wire.codec import (
    allocate_result_from_json,
    allocate_result_to_json,
    node_info_from_json,
    node_info_to_json,
    pod_info_from_json,
    pod_info_to_json,
)
from kubetpu.wire.controller import ControllerServer
from kubetpu.wire.server import NodeAgentServer

__all__ = [
    "AgentUnreachable",
    "ControllerServer",
    "NodeAgentServer",
    "probe_remote_agent",
    "RemoteDevice",
    "allocate_result_from_json",
    "allocate_result_to_json",
    "node_info_from_json",
    "node_info_to_json",
    "pod_info_from_json",
    "pod_info_to_json",
]
