"""``ControllerServer`` — the control plane as a long-running process.

The reference's control plane IS Kubernetes: operators talk to the
kube-apiserver and the external KubeDevice core plugs into it. kubetpu owns
the core, so it owns this surface too — a daemon that holds the
``Cluster``, keeps agent-backed nodes fresh, auto-reschedules pods off dead
agents, and serves a small operator HTTP API:

    GET    /healthz          liveness
    GET    /status           Cluster.status() snapshot (nodes, slices,
                             latency percentiles, recent events)
    GET    /metrics          FLEET-FEDERATED Prometheus text: controller
                             registry (scheduler latency summaries,
                             breaker-state / chips / pending gauges)
                             merged with every agent's /metrics scrape,
                             agent series relabeled node="<name>"
    GET    /trace/<id>       one stitched trace: controller spans merged
                             with each agent's /trace/<id> leg
    GET    /events           the controller's structured event log
                             (breaker transitions, drains, registrations)
                             as JSON Lines, trace-id cross-linked
    GET    /slo              last fleet-SLO evaluation (``slos=`` declares
                             objectives; evaluated per reconcile pass over
                             the federated /metrics) + firing list
    POST   /nodes            {"url": ..., "token"?: ...} -> register agent
    GET    /nodes            node name -> {url, free chips, pods}
    POST   /pods             {"pod": PodInfo} or {"gang": [PodInfo, ...]}
                             -> placements + per-container AllocateResult
                             (the env/devices a launcher starts the job
                             with); 409 when nothing fits. A pod carrying
                             the kubetpu/priority pseudo-resource may
                             preempt lower-priority pods when nothing
                             fits — victims are returned under "evicted"
                             and join the pending queue for automatic
                             re-placement
    GET    /pods/<name>      launcher env for an already-placed pod
    POST   /defrag           {"chips": N, "device"?, "max_migrations"?,
                             "execute"?, "pending"?: PodInfo} -> migration
                             plan (and its execution); 409 when no plan
                             within budget opens the block
    DELETE /pods/<name>      release a placed pod

A background poll loop refreshes every remote node on an interval; pods
evicted by a dead agent are automatically rescheduled onto surviving
nodes (pods that fit nowhere stay in a pending queue, retried each poll —
elastic recovery as a service, SURVEY.md §5.3). All Cluster mutations are
serialized under one lock; the HTTP layer is threaded.

Round-7 fault tolerance:

- node health is a CIRCUIT-BREAKER state machine, not one-strike: a
  missed probe moves a node healthy -> suspect (health-cordoned: no new
  placements, existing pods KEPT); only ``dead_after`` consecutive misses
  evict (fail_node -> reschedule), so a transient partition or agent GC
  pause no longer tears down and re-places whole gangs. A recovering
  node passes through probation (``probation_passes`` clean probes)
  before taking new work;
- ``POST /pods`` honors the ``Idempotency-Key`` header: a client retry
  whose first response was lost replays the committed placement instead
  of double-placing (only success is cached; a failed attempt's key is
  released so the retry re-executes);
- graceful lifecycle: ``drain_server()`` refuses new mutating work (503)
  while in-flight requests finish; ``shutdown(graceful=True)`` waits for
  them (bounded) before closing the listener;
- ``faults=`` installs a seeded ``FaultInjector`` into this server for
  chaos testing (``wire.faults``).

Shared-secret auth: like the agent server, a ``token`` protects every
route except ``/healthz`` (``KUBETPU_WIRE_TOKEN`` in the CLI).
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from kubetpu.api import utils
from kubetpu.core import Cluster, SchedulingError
from kubetpu.core.cluster import GangKey, _reset_for_reschedule, pod_priority
from kubetpu.core.journal import Journal
from kubetpu.obs import trace as obs_trace
from kubetpu.obs.events import EventLog
from kubetpu.obs.registry import Registry, federate, install_process_gauges
from kubetpu.obs.slo import Objective, SloEngine
from kubetpu.scheduler import meshstate
from kubetpu.scheduler.deviceclass import GPU, TPU
from kubetpu.scheduler.translate import pod_device_count, pod_wants_device
from kubetpu.wire.codec import (
    allocate_result_to_json,
    pod_info_from_json,
    pod_info_to_json,
)
from kubetpu.wire.httpcommon import (
    NO_RETRY,
    TRANSIENT_ERRORS,
    IdempotencyCache,
    InflightTracker,
    check_bearer,
    handle_guarded,
    request_json,
    request_text,
    run_idempotent,
    serve_events_jsonl,
    write_json,
    write_text,
)

class BadRequestError(Exception):
    """A malformed request VALUE — e.g. a vChip stamp outside the milli
    grammar — raised only by the controller's request-validation layer
    and mapped to a deterministic 400. Distinct from SchedulingError
    (409: well-formed but unplaceable) and from an internal ValueError
    (500: a server fault must not read as "your request is bad")."""


# circuit-breaker health states (healthy -> suspect -> probation -> dead)
HEALTHY = "healthy"
SUSPECT = "suspect"
PROBATION = "probation"


class NodeHealth:
    """Per-node breaker state: consecutive probe misses and, while
    recovering, consecutive clean probes."""

    __slots__ = ("state", "misses", "oks")

    def __init__(self) -> None:
        self.state = HEALTHY
        self.misses = 0
        self.oks = 0


class ControllerServer:
    """Operator API + reconcile loop over one ``Cluster``."""

    def __init__(
        self,
        cluster: Optional[Cluster] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        poll_interval: float = 5.0,
        token: Optional[str] = None,
        reserve_after: int = 3,
        reserve_hold: int = 10,
        suspect_after: int = 1,
        dead_after: int = 3,
        probation_passes: int = 1,
        faults=None,
        agent_retry=None,
        idem_window: float = 300.0,
        slos: Optional[List[Objective]] = None,
        journal_path: Optional[str] = None,
        journal_fsync: bool = False,
        journal_compact_bytes: int = 256 * 1024,
    ) -> None:
        """(Round-11 additions) *slos*: declarative fleet objectives
        (``obs.slo.fleet_slos(...)`` builds the standard set) evaluated
        over the controller's OWN federated ``/metrics`` after every
        reconcile pass — burn rates render as ``kubetpu_slo_*`` gauges
        and structured results serve at ``GET /slo``, the decision
        surface the autoscaling roadmap item consumes.

        (Round-20 crash tolerance) *journal_path*: an append-only,
        checksummed WAL of every state-mutating op, written BEFORE the
        client is acked — on boot the journal replays, agents are
        re-probed, placements re-pin through the normal scheduler, and
        the agents' actual allocation ledgers are reconciled against the
        replayed state (orphans freed, ghosts re-pended) before the
        control plane accepts mutations again (``/healthz`` reports
        ``recovering`` until then). *journal_fsync*: fsync per append
        (power-loss durability; default survives process SIGKILL).
        *journal_compact_bytes*: WAL size that triggers the periodic
        snapshot + truncation on the reconcile loop."""
        self.cluster = cluster or Cluster()
        self.poll_interval = poll_interval
        self.token = token or None
        # -- observability (Round-8): one registry for the whole control
        # plane. The cluster's scheduler latencies are re-homed into it
        # (same histograms, no second recording path); breaker-state /
        # capacity / queue gauges are collect-time callbacks so scrapes
        # read fresh state under the lock and mutations pay nothing.
        self.obs_component = "controller"
        self.registry = Registry()
        install_process_gauges(self.registry, "controller")
        # Round-11: structured event log (breaker transitions, drains,
        # registrations) at GET /events + fleet SLO engine at GET /slo
        self.events = EventLog(component="controller")
        self.slo: Optional[SloEngine] = (
            SloEngine(slos, registry=self.registry) if slos else None
        )
        self.cluster.metrics.bind(
            self.registry, "kubetpu_schedule_latency_seconds")
        for key in ("submits", "reconcile_passes",
                    "federation_scrape_errors"):
            # key ranges over the fixed literal tuple above — KTP004's
            # bounded-f-string proof expands and validates every name
            self.registry.counter(f"kubetpu_controller_{key}_total")
        for state in (HEALTHY, SUSPECT, PROBATION):
            self.registry.gauge_fn(
                "kubetpu_nodes",
                lambda s=state: self._count_health(s), state=state)
        self.registry.gauge_fn(
            "kubetpu_pending_pods", lambda: len(self._pending))
        for dc in (TPU, GPU):
            self.registry.gauge_fn(
                "kubetpu_chips_free",
                lambda r=dc.resource_name: self._chip_totals(r)[0],
                device=dc.resource_name)
            self.registry.gauge_fn(
                "kubetpu_chips_held",
                lambda r=dc.resource_name: self._chip_totals(r)[1],
                device=dc.resource_name)
        # Round-18 vChips: fractional placements made by this controller,
        # and per-chip occupancy gauges (labels are dynamic — refreshed
        # by _update_occupancy_gauges on every reconcile pass; a chip
        # that leaves the fleet reads 0.0, it cannot un-render)
        self._c_frac_allocs = self.registry.counter(
            "kubetpu_fractional_allocations_total",
            "vChip (fractional) pod placements")
        # node -> set of chip labels currently rendered (Round-21: keyed
        # per node so the incremental reconcile can retire one node's
        # chips without reconstructing the fleet view)
        self._occ_seen: Dict[str, set] = {}
        # Round-20 durable control plane: replay the WAL (if any) into a
        # recovered-state snapshot NOW; the actual re-probe/re-place/
        # reconcile runs in _recover() from start(), with the wire
        # answering 503 to mutations (healthz: "recovering") until the
        # reconciled state passes check_invariants().
        self.journal: Optional[Journal] = None
        self.journal_compact_bytes = journal_compact_bytes
        self._recovered_state: Optional[dict] = None
        self.recovering = False
        for key in ("orphans_freed", "ghosts_repended",
                    "placements_restored", "agents_unreachable",
                    "replays"):
            # key ranges over the fixed literal tuple above — KTP004's
            # bounded-f-string proof expands and validates every name
            self.registry.counter(f"kubetpu_recovery_{key}_total")
        if journal_path:
            self.journal = Journal(journal_path, fsync=journal_fsync)
            recovered = self.journal.replay_state()
            # EVERY journaled facet triggers recovery — a WAL whose
            # reduced state carries only operator cordons or a nonzero
            # gang_seq still has state to restore (dropping a cordon
            # silently, or re-issuing a replayed gang-id stamp, is as
            # much a crash-amnesia bug as a lost placement)
            if any(recovered[k] for k in
                   ("agents", "placements", "pending",
                    "cordons", "gang_seq")):
                self._recovered_state = recovered
                self.recovering = True
            journal = self.journal
            self.registry.gauge_fn(
                "kubetpu_journal_seq", lambda: journal.stats()["seq"])
            self.registry.gauge_fn(
                "kubetpu_journal_wal_bytes",
                lambda: journal.stats()["wal_bytes"])
            self.registry.gauge_fn(
                "kubetpu_journal_records_appended",
                lambda: journal.stats()["records_appended"])
            self.registry.gauge_fn(
                "kubetpu_journal_snapshots",
                lambda: journal.stats()["snapshots_written"])
            self.registry.gauge_fn(
                "kubetpu_journal_torn_tails",
                lambda: journal.stats()["torn_tail_dropped"])
        self.registry.gauge_fn(
            "kubetpu_controller_recovering",
            lambda: 1.0 if self.recovering else 0.0)
        # circuit-breaker thresholds: ``suspect_after`` consecutive missed
        # probes health-cordon a node (pods kept, no new placements);
        # ``dead_after`` consecutive misses evict it. ``dead_after=1`` is
        # the legacy one-strike behavior. A recovering node must answer
        # ``probation_passes`` consecutive probes before taking work again.
        if dead_after < 1 or suspect_after < 1:
            raise ValueError("health thresholds must be >= 1")
        self.suspect_after = suspect_after
        self.dead_after = max(dead_after, suspect_after)
        self.probation_passes = max(probation_passes, 1)
        self._health: Dict[str, NodeHealth] = {}
        self._health_cordoned: set = set()  # cordons WE placed (not operator)
        self.faults = faults
        self.agent_retry = agent_retry  # RetryPolicy toward agents (None=default)
        self._idem = IdempotencyCache(ttl=idem_window)
        self.draining = False
        self._inflight = InflightTracker()
        # head-of-line gang reservation: a pending gang that has survived
        # this many reconcile passes claims the device classes it requests —
        # later pending work and new submissions of those classes queue
        # behind it instead of cherry-picking freed chips out from under it
        # (the classic big-gang starvation). 0 disables.
        self.reserve_after = reserve_after
        # a reservation expires after this many passes without assembling
        # (the gang is likely infeasible right now — e.g. sized for a node
        # that left): its aging restarts, blocked work flows again, and it
        # re-reserves if it keeps waiting. 0 = hold forever.
        self.reserve_hold = reserve_hold
        self._reserve_held: Dict[int, int] = {}  # gang id -> passes held
        self._lock = threading.Lock()
        self._node_urls: Dict[str, str] = {}
        self._pending: List = []  # evicted pods awaiting capacity
        self._pending_age: Dict[str, int] = {}  # name -> reconcile passes
        self._stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        controller = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802
                utils.logf(5, "controller: " + fmt, *args)

            def _reply(self, code: int, obj) -> None:
                write_json(self, code, obj)

            def _authorized(self) -> bool:
                if check_bearer(self.headers, controller.token):
                    return True
                self._reply(401, {"error": "missing or invalid bearer token"})
                return False

            def _body(self) -> dict:
                length = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(length) or b"{}")

            def do_GET(self):  # noqa: N802
                handle_guarded(controller, self, self._do_get)

            def _do_get(self):
                # NOTE: payloads are built under the lock but written to the
                # socket OUTSIDE it — one stalled reader must never block
                # scheduling or reconciliation.
                if self.path == "/healthz":
                    self._reply(200, {"ok": True,
                                      "draining": controller.draining,
                                      "recovering": controller.recovering})
                    return
                if not self._authorized():
                    return
                if self.path == "/status":
                    with controller._lock:
                        out = controller.cluster.status()
                    self._reply(200, out)
                elif self.path == "/metrics":
                    # fleet federation: own registry + every agent's scrape
                    # (relabeled node="...") + the Cluster gauges — built
                    # OUTSIDE the lock (the gauge callbacks take it briefly
                    # per read; a slow agent scrape must not freeze the
                    # operator API)
                    write_text(self, 200, controller._metrics_text())
                elif self.path.startswith("/trace/"):
                    tid = self.path[len("/trace/"):]
                    self._reply(200, controller._trace(tid))
                elif self.path.split("?")[0] == "/events":
                    serve_events_jsonl(self, controller.events.to_jsonl)
                elif self.path == "/slo":
                    self._reply(200, {
                        "slos": (controller.slo.results()
                                 if controller.slo is not None else {}),
                        "firing": (controller.slo.firing()
                                   if controller.slo is not None else []),
                    })
                elif self.path == "/nodes":
                    with controller._lock:
                        status = controller.cluster.status()["nodes"]
                        out = {
                            name: {
                                **entry,
                                "url": controller._node_urls.get(name),
                                "health": controller._health_state(name),
                            }
                            for name, entry in status.items()
                        }
                    self._reply(200, out)
                elif self.path.startswith("/pods/"):
                    # launcher env for an already-placed pod (idempotent:
                    # device allocate only derives env from AllocateFrom) —
                    # how a launcher recovers env after a reconcile re-place
                    name = self.path[len("/pods/"):]
                    try:
                        out = controller._allocate_existing(name)
                        self._reply(200, {"pod": name, "containers": out})
                    except KeyError:
                        self._reply(404, {"error": f"no pod {name!r}"})
                    except Exception as e:  # noqa: BLE001
                        self._reply(500, {"error": str(e)})
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802
                handle_guarded(controller, self, self._do_post)

            def _submit_leg(self):
                """/pods execution leg for run_idempotent: the draining
                refusal lives here, AFTER the replay lookup — a keyed
                retry of an already-committed submit gets its replay even
                mid-drain (replaying mutates nothing)."""
                if controller.draining:
                    return 503, {"error": "controller is draining"}
                return 200, controller._submit(self._body())

            def _do_post(self):
                if not self._authorized():
                    return
                if controller.recovering:
                    # the wire stays closed to mutations until replay +
                    # reconciliation pass check_invariants — a 503 so a
                    # keyed client retry re-executes once we're open
                    self._reply(503, {"error": "controller is recovering"})
                    return
                if controller.draining and self.path != "/pods":
                    self._reply(503, {"error": "controller is draining"})
                    return
                try:
                    if self.path == "/nodes":
                        req = self._body()
                        name = controller.register_agent(
                            req["url"], name=req.get("name"),
                            token=req.get("token"),
                        )
                        self._reply(200, {"node": name})
                    elif self.path == "/pods":
                        # _submit manages the lock itself: placement commits
                        # under it, the per-container agent wire calls run
                        # OUTSIDE it (a slow-but-alive agent must not freeze
                        # /status, /nodes, DELETE and the reconcile pass).
                        # Idempotency-keyed retries replay the committed
                        # placement instead of double-placing (the shared
                        # run_idempotent contract; exceptions abort the key
                        # and fall through to the error mapping below).
                        run_idempotent(
                            self, controller._idem,
                            self.headers.get("Idempotency-Key"),
                            self._submit_leg,
                        )
                    elif self.path == "/defrag":
                        req = self._body()
                        with controller._lock:
                            out = controller._defrag(req)
                        self._reply(200, out)
                    elif (
                        len(parts := self.path.split("/")) == 4
                        and parts[1] == "nodes"
                        and parts[3] in ("cordon", "uncordon", "drain")
                    ):
                        # exactly /nodes/<name>/<action> — a malformed path
                        # must 404, never flip a cordon by accident
                        name, action = parts[2], parts[3]
                        try:
                            if action == "drain":
                                out = controller._drain(name)
                            else:
                                with controller._lock:
                                    controller.cluster.cordon(
                                        name, on=action == "cordon")
                                    # journaled inside the same critical
                                    # section that flipped the cordon:
                                    # WAL order must match apply order
                                    # when a concurrent un/cordon races
                                    controller._journal(
                                        "cordon", name=name,
                                        on=action == "cordon")
                                out = {action: name}
                            self._reply(200, out)
                        except KeyError:
                            self._reply(404, {"error": f"no node {name!r}"})
                    else:
                        self._reply(404, {"error": f"no route {self.path}"})
                except BadRequestError as e:
                    # a malformed request value (e.g. a vChip stamp
                    # outside the milli grammar) is the CLIENT's error —
                    # a deterministic 400, never a retryable-looking 500.
                    # Only the request-validation layer raises this; an
                    # internal ValueError still surfaces as a 500 (a
                    # server fault must not read as "don't retry, your
                    # request is bad").
                    self._reply(400, {"error": str(e)})
                except SchedulingError as e:
                    self._reply(409, {"error": str(e)})
                except TRANSIENT_ERRORS as e:
                    # an agent wire leg died mid-request (state rolled
                    # back): transient infra, answered 503 so a keyed
                    # client retry re-executes instead of surfacing a
                    # dead-end 500. The WHOLE transient family, not just
                    # ConnectionError — during an agent's kill->restart
                    # window the escape is as often a connection-reset
                    # OSError, a TimeoutError or an httplib
                    # RemoteDisconnected, and a plain 500 is terminal
                    # for keyed retries (the client never re-executes)
                    self._reply(503, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — report, stay up
                    self._reply(500, {"error": str(e)})

            def do_DELETE(self):  # noqa: N802
                handle_guarded(controller, self, self._do_delete)

            def _do_delete(self):
                if not self._authorized():
                    return
                if controller.recovering:
                    self._reply(503, {"error": "controller is recovering"})
                    return
                if controller.draining:
                    # DELETE mutates cluster state too: a draining control
                    # plane must be FROZEN, not merely not-placing
                    self._reply(503, {"error": "controller is draining"})
                    return
                if not self.path.startswith("/pods/"):
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                name = self.path[len("/pods/"):]
                release_target = None
                with controller._lock:
                    try:
                        node_name = controller.cluster.pod_node(name)
                        controller.cluster.release(name)
                        if node_name is not None:
                            # a released vChip share must leave the
                            # occupancy gauge immediately, not at the
                            # next submit that happens to touch the node
                            controller._update_occupancy_gauges(
                                only_nodes={node_name})
                            url = controller._node_urls.get(node_name)
                            if url is not None:
                                release_target = (
                                    url,
                                    controller._agent_token(node_name))
                        out = {"released": name}
                    except KeyError:
                        # a preemption/eviction victim waiting in the
                        # pending queue is deletable too — otherwise the
                        # next reconcile pass resurrects a pod the
                        # operator tried to remove
                        before = len(controller._pending)
                        controller._pending = [
                            p for p in controller._pending if p.name != name
                        ]
                        if len(controller._pending) < before:
                            # drop the age too: a same-name resubmission
                            # must not inherit it and reserve instantly
                            controller._pending_age.pop(name, None)
                            out = {"released": name, "was_pending": True}
                        else:
                            out = None
                    if out is not None:
                        # journal BEFORE the ack AND inside the same
                        # critical section that applied the release: a
                        # keyed submit reusing the name the instant the
                        # lock drops must journal its pod_place AFTER
                        # this record, or a replay deletes the NEW
                        # placement (WAL order must match apply order;
                        # the journal's own lock makes holding ours
                        # across the append safe)
                        controller._journal("pod_delete", name=name)
                if out is None:
                    self._reply(404, {"error": f"no pod {name!r}"})
                    return
                # tell the agent to forget its ledger entry —
                # best-effort and OUTSIDE the lock: the ledger is
                # reconciliation metadata, and a dark agent's entry is
                # freed as an orphan at the next cold restart anyway
                if release_target is not None:
                    url, tok = release_target
                    try:
                        # deliberately unkeyed single attempt: the
                        # retry path for a lost release is the orphan
                        # reconcile at the next cold restart
                        # ktlint: disable=KTP002
                        request_json(url + "/release", {"pod": name},
                                     token=tok, timeout=5.0,
                                     retry=NO_RETRY)
                    except Exception:  # noqa: BLE001 — best-effort
                        pass
                self._reply(200, out)

        self._httpd = ThreadingHTTPServer((host, port), Handler)

    # -- scheduling ----------------------------------------------------------

    def register_agent(
        self, url: str, name: Optional[str] = None, token: Optional[str] = None
    ) -> str:
        """Register a live agent (the one registration path — the POST
        /nodes handler and the CLI both call this). The wire probe runs
        OUTSIDE the cluster lock: a black-holed URL must cost the caller a
        timeout, not stall the whole operator API. Re-registering the SAME
        name at the SAME url is a no-op returning the name — a retried
        registration whose first response was lost must not 500."""
        from kubetpu.wire.client import probe_remote_agent

        dev, info = probe_remote_agent(
            url, name=name, token=token, retry=self.agent_retry
        )
        with self._lock:
            if info.name in self.cluster.nodes:
                if self._node_urls.get(info.name) == url:
                    # idempotent re-register — and the probe above just
                    # SUCCEEDED, so any accumulated miss streak is over:
                    # reset the breaker and lift our health cordon (a
                    # freshly verified-alive node must not be one blip
                    # from eviction)
                    self._health[info.name] = NodeHealth()
                    self._health_uncordon(info.name)
                    return info.name
                raise ValueError(
                    f"node {info.name!r} is already registered; remove it "
                    f"first, or start the agent with a distinct --name"
                )
            self.cluster._event("register_remote", node=info.name, url=url)
            self.events.emit("register", node=info.name, url=url)
            self.cluster.register_node(
                info.name, device=dev, node_info=info, probe=False
            )
            self._node_urls[info.name] = url
            self._health[info.name] = NodeHealth()
            self._journal("node_register", name=info.name, url=url,
                          token=token)
            return info.name

    # -- durable journal (Round-20) ------------------------------------------

    def _journal(self, kind: str, **data) -> None:
        """Durably record one state-mutating op BEFORE its ack — a no-op
        without ``journal_path``. The journal has its own lock; callers
        may hold the cluster lock or not."""
        if self.journal is not None:
            self.journal.append(kind, data)

    def _journal_state_locked(self) -> dict:
        """The live state as a journal snapshot — caller holds the lock.
        Built from the AUTHORITATIVE structures (cluster + queues), so a
        compaction self-heals any drift an unjournaled corner left in
        the WAL's reduced view."""
        placements = {}
        for nn, node in self.cluster.nodes.items():
            for pname, placed in node.pods.items():
                placements[pname] = {
                    "pod": pod_info_to_json(_reset_for_reschedule(placed)),
                    "node": nn,
                }
        agents = {}
        for name, url in self._node_urls.items():
            node = self.cluster.nodes.get(name)
            agents[name] = {
                "url": url,
                "token": getattr(
                    getattr(node, "device", None), "token", None),
            }
        return {
            "agents": agents,
            "placements": placements,
            "pending": [pod_info_to_json(p) for p in self._pending],
            # health cordons re-derive from live probes after a restart;
            # persisting them would leave a node cordoned with no breaker
            # state to ever lift it
            "cordons": sorted(
                self.cluster.cordoned - self._health_cordoned),
            "gang_seq": self.cluster._gang_seq,
        }

    def compact_journal(self) -> None:
        """Snapshot the live state and truncate the WAL (the reconcile
        loop calls this when the WAL crosses ``journal_compact_bytes``;
        operators can force it)."""
        if self.journal is None:
            return
        with self._lock:
            state = self._journal_state_locked()
        seq = self.journal.snapshot(state)
        self.events.emit("journal_compact", seq=seq)

    # -- circuit-breaker node health -----------------------------------------

    def _health_state(self, name: str) -> str:
        """Call under the lock. Nodes without breaker state (in-process
        devices, never probed) read healthy."""
        h = self._health.get(name)
        return h.state if h is not None else HEALTHY

    def _health_cordon(self, name: str) -> None:
        """Health-cordon (under the lock): no NEW placements while the
        node is suspect/probation; existing pods stay. Operator cordons
        are left alone — we only lift cordons WE placed."""
        if name not in self.cluster.cordoned:
            self.cluster.cordon(name)
            self._health_cordoned.add(name)

    def _health_uncordon(self, name: str) -> None:
        if name in self._health_cordoned:
            self._health_cordoned.discard(name)
            if name in self.cluster.nodes:
                self.cluster.cordon(name, on=False)

    def _record_miss(self, name: str) -> bool:
        """One missed probe (under the lock). Returns True when the node
        crossed ``dead_after`` consecutive misses and must be evicted."""
        h = self._health.setdefault(name, NodeHealth())
        h.misses += 1
        h.oks = 0
        if h.misses >= self.dead_after:
            self._health.pop(name, None)
            self._health_cordoned.discard(name)  # remove_node drops the cordon
            return True
        if h.state != SUSPECT and h.misses >= self.suspect_after:
            h.state = SUSPECT
            self._health_cordon(name)
            self.cluster._event("node_suspect", node=name, misses=h.misses)
            self.events.emit("node_suspect", node=name, misses=h.misses)
        return False

    def _record_ok(self, name: str) -> None:
        """One clean probe (under the lock): suspect -> probation on the
        first clean probe, then healthy after ``probation_passes`` MORE
        consecutive clean probes (the node stays health-cordoned through
        probation — a flapping agent must prove itself before taking new
        work; its existing pods ran undisturbed the whole time)."""
        h = self._health.get(name)
        if h is None:
            return
        # a clean probe ALWAYS zeroes the miss streak — dead_after counts
        # CONSECUTIVE misses, so a healthy-but-flapping node (miss, ok,
        # miss, ok, ...) must never accumulate toward suspect/dead
        h.misses = 0
        if h.state == HEALTHY:
            return
        if h.state == SUSPECT:
            h.state = PROBATION
            h.oks = 0
            self.cluster._event("node_probation", node=name)
            self.events.emit("node_probation", node=name)
            return
        h.oks += 1
        if h.oks >= self.probation_passes:
            h.state = HEALTHY
            h.oks = 0
            self._health_uncordon(name)
            self.cluster._event("node_recovered", node=name)
            self.events.emit("node_recovered", node=name)

    def _snapshot_placed(self, name: str, node_name: Optional[str] = None):
        """(device, pod copy) of a placed pod — caller holds the lock.
        The copy is what the wire phase works from, so cluster state can
        keep moving while agent HTTP calls are in flight. Pass *node_name*
        when known (every just-placed pod carries it): the name-only scan
        is O(nodes) and runs under the lock."""
        if node_name is not None:
            node = self.cluster.nodes.get(node_name)
            placed = node.pods.get(name) if node is not None else None
            if placed is None:
                raise KeyError(name)
            return node.device, placed.copy()
        for node in self.cluster.nodes.values():
            placed = node.pods.get(name)
            if placed is not None:
                return node.device, placed.copy()
        raise KeyError(name)

    @staticmethod
    def _run_allocations(device, pod_copy) -> dict:
        """Container-start allocation from a snapshot — wire calls, NO lock
        held. Mirrors Cluster.allocate's container order."""
        out = {}
        for cname in sorted(pod_copy.init_containers):
            out[cname] = allocate_result_to_json(
                device.allocate(pod_copy, pod_copy.init_containers[cname])
            )
        for cname in sorted(pod_copy.running_containers):
            out[cname] = allocate_result_to_json(
                device.allocate(pod_copy, pod_copy.running_containers[cname])
            )
        return out

    def _allocate_existing(self, name: str) -> dict:
        """Launcher env for a placed pod. The snapshot (pod copy + device)
        is taken under the lock; the per-container wire calls run outside
        it, so a slow-but-alive agent cannot freeze the control plane."""
        with self._lock:
            device, pod_copy = self._snapshot_placed(name)
        return self._run_allocations(device, pod_copy)

    def _pod_name_in_use(self, name: str) -> bool:
        """Placed anywhere OR waiting in the pending queue — the one
        authoritative name check for every pod-accepting route."""
        return any(
            name in node.pods for node in self.cluster.nodes.values()
        ) or any(p.name == name for p in self._pending)

    def _release_if_current(self, placed) -> bool:
        """Rollback release with IDENTITY revalidation — caller holds the
        lock. Releases only when the record at this name is still the very
        placement we made: a DELETE (or DELETE + same-name resubmit) during
        the lock-free wire phase wins, and our rollback must neither
        resurrect the deleted pod nor kill the unrelated new one. Returns
        True when this placement was released."""
        node = self.cluster.nodes.get(placed.node_name)
        if node is None or node.pods.get(placed.name) is not placed:
            return False
        self.cluster.release(placed.name)
        return True

    def _allocate_batch(self, items) -> list:
        """The shared wire tail of reconcile re-placement and drain:
        per-container agent allocations run OUTSIDE the lock (a
        slow-but-alive agent must not freeze the operator API); a failed
        allocation rolls back under the lock with identity revalidation
        (a pod DELETEd — or DELETEd and resubmitted under the same name —
        during the wire phase is neither resurrected into the pending
        queue nor released out from under the new owner), and its
        *pending_template* joins the queue for the next pass.

        ``items``: (pending_template, placed, device, pod_copy) tuples;
        returns {pod, node, containers} dicts for the successes."""
        done, rollbacks = [], []
        for template, placed, device, pod_copy in items:
            try:
                done.append({
                    "pod": placed.name,
                    "node": placed.node_name,
                    "containers": self._run_allocations(device, pod_copy),
                })
                self._journal("pod_place",
                              pod=pod_info_to_json(template),
                              node=placed.node_name)
            except Exception as e:  # noqa: BLE001 — allocate leg died
                utils.errorf("allocate failed for %s: %s", placed.name, e)
                rollbacks.append((template, placed))
        if rollbacks:
            with self._lock:
                for template, placed in rollbacks:
                    if self._release_if_current(placed):
                        self._pending.append(template)
                        self._journal("pod_pending",
                                      pod=pod_info_to_json(template))
        return done

    def _drain(self, name: str) -> dict:
        """Cordon + migrate a node's pods (operator maintenance). The
        _submit pattern: migrations commit under the lock, the agent wire
        allocations for the NEW placements run outside it, failed
        allocations roll back into the pending queue. Pods that fit
        nowhere else pend for the reconcile loop (they re-place the moment
        capacity appears — the node is already cordoned, so never back
        onto it)."""
        with self._lock:
            res = self._active_reservation()
            migrated, unplaced = self.cluster.drain(  # KeyError -> 404
                name,
                # drained pods respect the gang reservation like every
                # other placement path; blocked ones pend behind the gang.
                # Slice-pinned SURVIVORS of a placed gang are exempt (as on
                # the reconcile path): they can only re-place inside their
                # mates' slice, which cannot cherry-pick reserved capacity,
                # and stranding them would break a running gang.
                may_place=lambda p: (
                    self.cluster.gang_slice_filter(p) is not None
                    or not self._reservation_blocks(res, [p])
                ),
            )
            self._pending.extend(unplaced)
            snapshots = [
                (_reset_for_reschedule(p), p,
                 *self._snapshot_placed(p.name, p.node_name))
                for p in migrated
            ]
            # the drain cordoned the node and pended what fit nowhere —
            # journaled inside the same critical section that applied
            # them, so WAL order matches apply order under concurrent
            # mutations; the migrated re-placements journal from
            # _allocate_batch below
            self._journal("cordon", name=name, on=True)
            for p in unplaced:
                self._journal("pod_pending", pod=pod_info_to_json(p))
        self.events.emit("drain", node=name, migrated=len(migrated),
                         unplaced=len(unplaced))
        out = {"drained": name,
               "migrated": self._allocate_batch(snapshots)}
        with self._lock:
            out["pending"] = [q.name for q in self._pending]
        return out

    # -- gang reservation (starvation guard) ---------------------------------

    def _active_reservation(self) -> Optional[dict]:
        """Call under the lock. The FIRST pending gang aged
        ``reserve_after``+ reconcile passes holds the reservation:
        {"gang": id, "classes": {resource names}, "priority": max}."""
        if not self.reserve_after:
            return None
        for p in self._pending:
            gid = p.requests.get(GangKey)
            if gid is None:
                continue
            if self.cluster.gang_slice_filter(p) is not None:
                # surviving member of a PARTIALLY-PLACED gang: it can only
                # re-join its mates' slice, so it must not freeze the whole
                # device class cluster-wide
                continue
            if self._pending_age.get(p.name, 0) >= self.reserve_after:
                members = [
                    q for q in self._pending
                    if q.requests.get(GangKey) == gid
                ]
                classes = {
                    dc.resource_name
                    for dc in (TPU, GPU)
                    for q in members
                    if pod_wants_device(dc, q)
                }
                prio = max(pod_priority(q) for q in members)
                return {"gang": gid, "classes": classes, "priority": prio}
        return None

    def _reservation_blocks(self, res: Optional[dict], pods) -> bool:
        """Does the active reservation forbid placing *pods* now? The
        reserved gang itself always passes; so do pods of other device
        classes and pods that OUTRANK the gang (priority preemption keeps
        working during a reservation)."""
        if not res:
            return False
        if all(p.requests.get(GangKey) == res["gang"] for p in pods):
            return False
        wants = {
            dc.resource_name
            for dc in (TPU, GPU)
            for p in pods
            if pod_wants_device(dc, p)
        }
        if not (wants & res["classes"]):
            return False
        return max(pod_priority(p) for p in pods) <= res["priority"]

    def _enqueue_locked(self, req: dict, pods) -> dict:
        """Queue a submission instead of placing it (``"queue": true``).
        Gang submissions get a fresh gang-identity stamp NOW so the
        reconcile pass re-places the members atomically (and so the gang
        can itself age into a reservation). Requests exceeding the
        cluster's TOTAL capacity of a class are refused outright — they
        could never leave the queue, but could age into a reservation that
        soft-locks the class (resubmit after adding nodes)."""
        for dc in (TPU, GPU):
            want = sum(pod_device_count(dc, p) for p in pods)
            if want > 0:
                have = sum(
                    int(n.info.capacity.get(dc.resource_name, 0))
                    for n in self.cluster.nodes.values()
                )
                if want > have:
                    raise SchedulingError(
                        f"request for {want} x {dc.resource_name} exceeds "
                        f"total cluster capacity ({have}); refusing to "
                        f"queue a submission that cannot ever place"
                    )
        if "gang" in req:
            gid = self.cluster.new_gang_id()
            for p in pods:
                p.requests[GangKey] = gid
        self._pending.extend(pods)
        for p in pods:
            self._journal("pod_pending", pod=pod_info_to_json(p))
        return {"queued": [p.name for p in pods]}

    def _submit(self, req: dict) -> dict:
        """Span + counter shell around ``_submit_inner`` — a submit is the
        control plane's marquee operation, so it gets its own span (child
        of the HTTP server span, parent of the per-container agent
        allocate calls)."""
        self.registry.counter("kubetpu_controller_submits_total").inc()
        with obs_trace.span("controller.submit", component="controller") as sp:
            sp.tag(pods=len(req.get("gang", [])) or 1,
                   gang="gang" in req)
            return self._submit_inner(req)

    def _submit_inner(self, req: dict) -> dict:
        """Place a pod or a gang and run container-start allocation — the
        caller gets everything a launcher needs. Manages the lock itself,
        in three phases (the _allocate_existing pattern, ADVICE r2):
        placement commits under the lock; the per-container agent wire
        calls run OUTSIDE it from snapshots; on allocate failure the lock
        is re-acquired to roll back (release + restore victims). The
        placement is visible to other routes during the wire phase — a
        concurrent DELETE wins, and the rollback's release tolerates it.
        All-or-nothing: an allocate failure (e.g. the agent died since
        placement) releases everything placed here before re-raising."""
        if "gang" in req:
            pods = [pod_info_from_json(p) for p in req["gang"]]
        else:
            pods = [pod_info_from_json(req["pod"])]
        for p in pods:
            try:
                meshstate.pod_milli(p)
            except ValueError as e:
                # validate vChip stamps at the wire boundary: a malformed
                # milli value is the client's deterministic 400, not a
                # ValueError escaping mid-schedule as a retryable 500
                raise BadRequestError(str(e)) from e
        names = [p.name for p in pods]
        if len(set(names)) != len(names):
            raise SchedulingError(f"duplicate pod names in request: {names}")
        evicted: List = []
        queue = bool(req.get("queue"))
        with self._lock:
            for n in names:
                if self._pod_name_in_use(n):
                    # a duplicate submit would silently overwrite the placed
                    # record and leak its resources (Cluster.schedule keys
                    # node.pods by name)
                    raise SchedulingError(f"pod name {n!r} is already in use")
            res = self._active_reservation()
            if self._reservation_blocks(res, pods):
                if queue:
                    return self._enqueue_locked(req, pods)
                raise SchedulingError(
                    f"capacity is reserved for pending gang {res['gang']} "
                    f"(waiting {self.reserve_after}+ reconcile passes); "
                    f'submit with "queue": true to wait behind it, or '
                    f"outrank it via the priority pseudo-resource"
                )
            try:
                if "gang" in req:
                    placed = self.cluster.schedule_gang(pods)
                    contiguity = self.cluster.gang_contiguity(placed)
                else:
                    contiguity = None
                    if pod_priority(pods[0]) > 0:
                        # the priority pseudo-resource opts the pod into
                        # preemption (no separate schedule try:
                        # schedule_preempting already places without evicting
                        # when the pod fits plainly); victims join the
                        # pending queue and re-place automatically on the
                        # next reconcile pass, wherever capacity allows
                        placed_pod, evicted = self.cluster.schedule_preempting(
                            pods[0])
                        placed = [placed_pod]
                        self._pending.extend(evicted)
                    else:
                        placed = [self.cluster.schedule(pods[0])]
            except SchedulingError:
                if queue:
                    # doesn't fit NOW: wait for capacity instead of erroring
                    return self._enqueue_locked(req, pods)
                raise
            snapshots = [
                (p, *self._snapshot_placed(p.name, p.node_name))
                for p in placed
            ]
            self._update_occupancy_gauges(
                only_nodes={p.node_name for p in placed})
        evicted_names = [p.name for p in evicted]
        out = {"placements": []}
        try:
            for p, device, pod_copy in snapshots:
                out["placements"].append({
                    "pod": p.name,
                    "node": p.node_name,
                    "containers": self._run_allocations(device, pod_copy),
                })
            # count COMMITTED fractional placements only: a rolled-back
            # submit (below) is released and must not inflate the
            # monotonic counter — it re-pends and is counted when its
            # allocation actually lands
            self._count_fractional(placed)
            # journal BEFORE the ack, AFTER the wire phase survived: a
            # rolled-back submit writes nothing (the journal never saw
            # it), a crash after these appends replays the committed
            # placements. Victims journal as pending — replay moves them
            # out of their recorded placements the same way the live
            # path did.
            for p in placed:
                self._journal(
                    "pod_place",
                    pod=pod_info_to_json(_reset_for_reschedule(p)),
                    node=p.node_name)
            for v in evicted:
                self._journal("pod_pending", pod=pod_info_to_json(v))
        except Exception:
            # all-or-nothing INCLUDING preemption: release what this request
            # placed, then put the victims back where they were — a failed
            # submit must not disrupt running workloads
            with self._lock:
                node = placed[0].node_name if placed else ""
                for p in placed:
                    self._release_if_current(p)
                touched = {p.node_name for p in placed}
                if evicted:
                    self._pending = [
                        p for p in self._pending if p.name not in evicted_names
                    ]
                    # a victim the reconcile pass already re-placed during
                    # the wire phase must not be restored AGAIN (double
                    # placement); _pod_name_in_use now sees only placements
                    # (the pending entries were just filtered out)
                    to_restore = [
                        p for p in evicted if not self._pod_name_in_use(p.name)
                    ]
                    lost = self.cluster._restore_pods(to_restore, node)
                    for p in lost:  # could not restore: keep for reconcile
                        self._pending.append(p)
                    # restored victims may have landed on a FALLBACK node
                    # (the restore schedules a copy; look up where it
                    # went) — its occupancy gauge must move now, not at
                    # the next reconcile sweep (same standard as DELETE)
                    restored = {p.name for p in to_restore} - {
                        p.name for p in lost}
                    touched.update(
                        nn for nn, n in self.cluster.nodes.items()
                        if restored & set(n.pods))
                self._update_occupancy_gauges(only_nodes=touched)
            raise
        if contiguity is not None:
            out["gang_contiguity"] = contiguity
        if evicted_names:
            out["evicted"] = evicted_names
        return out

    def _defrag(self, req: dict) -> dict:
        """Plan (and optionally execute) a defragmentation. Caller holds
        the lock. Body: {"chips": N, "device"?: "tpu"|"gpu",
        "max_migrations"?: M, "execute"?: bool, "pending"?: PodInfo}."""
        chips = int(req["chips"])
        # the plan search is combinatorial in max_migrations and runs under
        # the global lock — cap what a client may request
        max_migrations = min(int(req.get("max_migrations", 3)), 5)
        if "pending" in req:
            pending_name = req["pending"].get("name", "")
            if self._pod_name_in_use(pending_name):
                raise SchedulingError(
                    f"pod name {pending_name!r} is already in use"
                )
        plan = self.cluster.defrag_plan(
            chips,
            max_migrations=max_migrations,
            device=req.get("device", "tpu"),
        )
        if plan is None:
            raise SchedulingError(
                f"no defrag plan within the migration budget opens a "
                f"{chips}-device block"
            )
        out = {
            "plan": [
                {"pod": m.pod_name, "from": m.from_node, "to": m.to_node}
                for m in plan
            ]
        }
        if req.get("execute"):
            pending = (
                pod_info_from_json(req["pending"]) if "pending" in req else None
            )
            if pending is not None:
                try:
                    meshstate.pod_milli(pending)
                except ValueError as e:
                    raise BadRequestError(str(e)) from e
            moved, placed_pending = self.cluster.execute_defrag(plan, pending)
            out["moved"] = [
                {"pod": p.name, "node": p.node_name} for p in moved
            ]
            for p in moved:
                self._journal(
                    "pod_place",
                    pod=pod_info_to_json(_reset_for_reschedule(p)),
                    node=p.node_name)
            if placed_pending is not None:
                out["pending_pod"] = {
                    "pod": placed_pending.name,
                    "node": placed_pending.node_name,
                }
                self._journal(
                    "pod_place",
                    pod=pod_info_to_json(
                        _reset_for_reschedule(placed_pending)),
                    node=placed_pending.node_name)
        return out

    # -- observability (Round-8) ---------------------------------------------

    def _count_health(self, state: str) -> int:
        with self._lock:
            return sum(
                1 for name in self.cluster.nodes
                if self._health_state(name) == state
            )

    def _count_fractional(self, placed_pods) -> None:
        """Tally vChip placements into the Round-18 counter."""
        n = sum(
            1 for p in placed_pods if p.requests.get(meshstate.FracKey)
        )
        if n:
            self._c_frac_allocs.inc(n)

    def _update_occupancy_gauges(self, only_nodes=None) -> None:
        """Refresh ``kubetpu_chip_occupancy_frac{node,chip}`` from the
        cluster's per-chip milli accounting — caller holds the lock.
        *only_nodes* scopes the refresh to the nodes a placement just
        touched (the submit hot path must not pay a fleet-wide sweep).

        Round-21: the reconcile pass (only_nodes=None) is INCREMENTAL
        too — it drains the cluster's dirty-node set (fed by the same
        accounting choke point the fit index uses) and touches only
        chips whose books changed since the last pass, so gauge upkeep
        stays flat at 4096+ chips instead of re-walking the fleet.
        Chips seen before but absent from a dirty node's fresh view
        (node died/removed, chip gone from a re-probe) are pinned to
        0.0 ONCE and dropped from the tracking map — a gauge cannot
        un-render, and a stale last-good occupancy would fake
        fragmentation on dead hardware, but re-zeroing departed chips
        every pass forever would be an unbounded tax on node churn."""
        if only_nodes is None:
            dirty = self.cluster.pop_dirty_occupancy()
            if not dirty:
                return
            occ = self.cluster.chip_occupancy(nodes=sorted(dirty))
        else:
            dirty = None
            occ = self.cluster.chip_occupancy(nodes=only_nodes)
        for node, per in occ.items():
            fresh = set()
            for chip, frac in per.items():
                fresh.add(str(chip))
                self.registry.gauge(
                    "kubetpu_chip_occupancy_frac",
                    node=node, chip=str(chip)).set(frac)
            if dirty is not None:
                # a re-probe can shrink a live node's chip set
                for chip in self._occ_seen.get(node, set()) - fresh:
                    self.registry.gauge(
                        "kubetpu_chip_occupancy_frac",
                        node=node, chip=chip).set(0.0)
                self._occ_seen[node] = fresh
            else:
                self._occ_seen.setdefault(node, set()).update(fresh)
        if dirty is not None:
            # dirty nodes with no occupancy view anymore: removed/dead
            # (or lost their vChip advertisement) — zero their chips once
            for node in dirty - set(occ):
                for chip in self._occ_seen.pop(node, set()):
                    self.registry.gauge(
                        "kubetpu_chip_occupancy_frac",
                        node=node, chip=chip).set(0.0)

    def _chip_totals(self, resource: str):
        """(free, held) chips of *resource* across the fleet. "Free"
        means WHOLE-chip free: fractional (vChip) placements never touch
        the scalar tally (exclusivity is derived at parse), so on
        vChip-capable nodes the count comes from the mesh state's free
        set — a chip packed solid with 250m tenants must not read as an
        idle chip on the fleet dashboard."""
        with self._lock:
            free = 0
            total = 0
            for n in self.cluster.nodes.values():
                total += int(n.info.capacity.get(resource, 0))
                state = (
                    meshstate.parse_mesh_state(n.info.allocatable)
                    if resource == TPU.resource_name else None
                )
                if state is not None and state.milli_key:
                    free += len(state.free)
                else:
                    free += int(n.info.allocatable.get(resource, 0))
        return free, total - free

    def _agent_token(self, name: str) -> Optional[str]:
        """The token that works toward THIS agent: the one its
        RemoteDevice authenticated registration with (register_agent
        accepts a per-agent token), falling back to the controller's."""
        node = self.cluster.nodes.get(name)
        token = getattr(getattr(node, "device", None), "token", None)
        return token or self.token

    def _scrape_agent_text(self, url: str, token: Optional[str]) -> str:
        """One text scrape of an agent endpoint through the shared
        retrying client (Round-12: the raw ``urlopen`` here bypassed
        retry/trace/fault injection — a chaos soak could never drop a
        federation scrape). ``NO_RETRY`` keeps the original semantics: a
        missed scrape is a gap in a graph, not an outage worth backoff,
        and the per-reconcile SLO evaluation must not stall failover
        behind a dark agent's backoff."""
        return request_text(url, token=token, timeout=5.0, retry=NO_RETRY)

    def _metrics_text(self) -> str:
        """The federated fleet exposition: this registry (scheduler
        latency summaries, breaker/capacity/queue gauges, controller
        counters) merged with every registered agent's ``/metrics``,
        agent series relabeled ``node="<name>"``. Scrape failures skip
        that agent and count — federation degrades, never 500s. Agents
        are scraped CONCURRENTLY (same shape as the reconcile probes):
        the per-reconcile SLO evaluation rides this path, so N dark
        agents must cost one timeout, not N sequential ones stalling
        failover and placement."""
        with self._lock:
            targets = {
                name: (url, self._agent_token(name))
                for name, url in self._node_urls.items()
            }
        scraped: Dict[str, str] = {}

        def scrape(item):
            name, (url, token) = item
            try:
                return name, self._scrape_agent_text(url + "/metrics", token)
            except Exception:  # noqa: BLE001 — degrade per agent
                self.registry.counter(
                    "kubetpu_controller_federation_scrape_errors_total").inc()
                return name, None

        if targets:
            with ThreadPoolExecutor(
                    max_workers=min(16, len(targets))) as pool:
                for name, text in pool.map(scrape, sorted(targets.items())):
                    if text is not None:
                        scraped[name] = text
        return federate(self.registry.render(), scraped)

    def _trace(self, trace_id: str) -> dict:
        """Stitch one trace: this process's spans plus every agent's
        ``/trace/<id>`` leg, deduplicated by span_id (in-process test
        stacks share the tracer; cross-process fleets don't), ordered by
        start time."""
        spans = {s["span_id"]: s
                 for s in obs_trace.tracer().spans(trace_id)}
        with self._lock:
            targets = {
                name: (url, self._agent_token(name))
                for name, url in self._node_urls.items()
            }
        for name, (url, token) in sorted(targets.items()):
            try:
                body = json.loads(self._scrape_agent_text(
                    f"{url}/trace/{trace_id}", token))
                for s in body.get("spans", []):
                    spans.setdefault(s["span_id"], s)
            except Exception:  # noqa: BLE001 — a dark agent loses its leg,
                pass           # not the whole trace
        ordered = sorted(spans.values(), key=lambda s: s["start"])
        return {"trace": trace_id, "spans": ordered}

    # -- reconcile loop ------------------------------------------------------

    def poll_once(self) -> dict:
        """One reconcile pass (see ``_poll_once``) wrapped in a root trace
        span — the reconcile loop runs with no inbound request to parent
        under, so each pass is its own trace. With fleet SLOs declared,
        each pass then evaluates them over the freshly-federated
        ``/metrics`` — the controller's evaluation window IS its
        reconcile cadence."""
        self.registry.counter(
            "kubetpu_controller_reconcile_passes_total").inc()
        with obs_trace.span("controller.reconcile", component="controller"):
            out = self._poll_once()
        if (self.journal is not None
                and self.journal.stats()["wal_bytes"]
                >= self.journal_compact_bytes):
            # periodic snapshot + compaction rides the reconcile cadence:
            # replay cost stays bounded by the knob, not by uptime
            self.compact_journal()
        if self.slo is not None:
            try:
                self.slo.evaluate(self._metrics_text())
            except Exception as e:  # noqa: BLE001 — judging must not
                utils.errorf("slo evaluation failed: %s", e)  # stop reconciling
        return out

    def _poll_once(self) -> dict:
        """One reconcile pass: probe remote agents (OUTSIDE the lock — a
        partition must not stall the operator API for timeout x agents),
        run missed probes through the circuit breaker (suspect/probation
        keep their pods; only ``dead_after`` consecutive misses evict),
        apply fresh advertisements, and re-place evicted + pending pods
        where capacity allows. Re-placed pods are allocated too, so their
        launcher env is ready (also at GET /pods/<name>)."""
        from kubetpu.api.types import new_node_info
        from kubetpu.wire import AgentUnreachable, RemoteDevice

        with self._lock:
            remotes = [
                (name, node.device)
                for name, node in sorted(self.cluster.nodes.items())
                if isinstance(node.device, RemoteDevice)
            ]
        probed: Dict[str, object] = {}
        dead: List[str] = []

        def probe(item):
            name, dev = item
            fresh = new_node_info(name)
            try:
                dev.update_node_info(fresh)
                return name, fresh, None
            except AgentUnreachable as e:
                return name, None, e
            except RuntimeError as e:  # degraded (HTTP 500), not dead
                utils.errorf("refresh of %s failed (degraded agent): %s", name, e)
                return name, None, None

        if remotes:
            # concurrent probes: a partition must cost one timeout per pass,
            # not one per dead agent
            with ThreadPoolExecutor(max_workers=min(16, len(remotes))) as pool:
                for name, fresh, err in pool.map(probe, remotes):
                    if fresh is not None:
                        probed[name] = fresh
                    elif err is not None:
                        dead.append(name)

        with self._lock:
            failed: List[str] = []
            suspect: List[str] = []
            for name in dead:
                if name not in self.cluster.nodes:
                    continue
                if self._record_miss(name):
                    # breaker tripped: dead_after consecutive misses
                    self._node_urls.pop(name, None)
                    self._pending.extend(self.cluster.fail_node(name))
                    failed.append(name)
                    self.events.emit("node_dead", node=name)
                    # replay moves the dead node's journaled placements
                    # to pending, mirroring the fail_node motion above
                    self._journal("node_dead", name=name)
                elif self._health_state(name) != HEALTHY:
                    # transient so far: pods stay placed, node is health-
                    # cordoned — a blip shorter than the threshold costs
                    # ZERO reschedules. (With suspect_after > 1 a node's
                    # first misses leave it HEALTHY and schedulable — it
                    # must not be reported suspect before the breaker
                    # actually opened.)
                    suspect.append(name)
            for name, fresh in probed.items():
                if name in self.cluster.nodes:
                    self._record_ok(name)
                    self.cluster.refresh_node(name, probed=fresh)
            # Phase 1 (under the lock): commit placements and snapshot; pods
            # that fit nowhere stay pending. Placed pods leave _pending NOW
            # so a concurrent DELETE sees them as placed, not pending.
            # An aged head-of-line gang reservation blocks later same-class
            # pending work this pass (starvation guard; the reserved gang
            # itself is tried in its FIFO turn). A reservation held past
            # reserve_hold passes without assembling expires: its aging
            # restarts so blocked work flows again (automatic recovery from
            # gangs the current cluster cannot satisfy).
            reservation = self._active_reservation()
            if reservation is not None:
                gid = reservation["gang"]
                held = self._reserve_held.get(gid, 0) + 1
                if self.reserve_hold and held > self.reserve_hold:
                    for q in self._pending:
                        if q.requests.get(GangKey) == gid:
                            # end-of-pass aging adds 1; land at 0
                            self._pending_age[q.name] = -1
                    self._reserve_held = {}
                    reservation = None
                    utils.logf(2, "reservation for gang %s expired after "
                               "%d passes; re-aging", gid, held - 1)
                else:
                    self._reserve_held = {gid: held}
            else:
                self._reserve_held = {}
            to_allocate, still_pending = [], []
            pending, consumed = list(self._pending), set()
            for i, pod in enumerate(pending):
                if i in consumed:
                    continue
                slice_filter = self.cluster.gang_slice_filter(pod)
                gid = pod.requests.get(GangKey)
                if gid and slice_filter is None:
                    # FULLY-evicted gang (no placed mates pin a slice):
                    # gather every pending member and re-place atomically
                    # via schedule_gang. Member-by-member would let the
                    # first land on a slice too small for the whole gang,
                    # pinning its mates to pend forever while it holds
                    # chips (ADVICE r2).
                    idxs = [
                        j for j in range(i, len(pending))
                        if j not in consumed
                        and pending[j].requests.get(GangKey) == gid
                    ]
                    consumed.update(idxs)
                    members = [pending[j] for j in idxs]
                    if self._reservation_blocks(reservation, members):
                        still_pending.extend(members)
                        continue
                    try:
                        placed_members = self.cluster.schedule_gang(members)
                    except SchedulingError:
                        still_pending.extend(members)
                        continue
                    orig = {m.name: m for m in members}
                    for placed in placed_members:
                        # schedule_gang stamped a FRESH gang id on the
                        # placed copies; propagate it to the templates so a
                        # member re-pended by an allocate failure still
                        # finds its (re-stamped) mates and keeps the
                        # single-slice affinity
                        orig[placed.name].requests[GangKey] = (
                            placed.requests[GangKey]
                        )
                        to_allocate.append((
                            orig[placed.name], placed,
                            *self._snapshot_placed(placed.name, placed.node_name),
                        ))
                    continue
                consumed.add(i)
                if slice_filter is None and self._reservation_blocks(
                        reservation, [pod]):
                    # plain pods wait behind the reserved gang; surviving-
                    # gang members (slice_filter set) are exempt — they
                    # re-join an already-placed gang, and stranding them
                    # would break it
                    still_pending.append(pod)
                    continue
                try:
                    # surviving-gang members re-place ONLY within their
                    # mates' slice — an unconstrained reschedule would
                    # silently straddle the gang over DCN, the exact
                    # failure schedule_gang refuses (core gang invariant)
                    if slice_filter is None and pod_priority(pod) > 0:
                        # a queued/evicted priority pod keeps its preemption
                        # semantics here, same as the direct-submit path —
                        # otherwise lower-priority work placed after it
                        # could pin it pending forever (priority inversion)
                        placed, victims = self.cluster.schedule_preempting(pod)
                        still_pending.extend(victims)
                    else:
                        placed = self.cluster.schedule(pod, slice_filter)
                    to_allocate.append(
                        (pod, placed,
                         *self._snapshot_placed(placed.name, placed.node_name))
                    )
                except SchedulingError:
                    still_pending.append(pod)
            self._pending = still_pending
            failed = sorted(failed)

        # Phases 2+3 (the _allocate_batch pattern): per-container agent
        # wire calls outside the lock, failed allocations rolled back
        # under it with identity revalidation, templates re-pended.
        rescheduled = self._allocate_batch(to_allocate)
        with self._lock:
            # age the queue: one pass survived = one tick; rebuilding the
            # dict drops entries for pods that placed (or were deleted)
            self._pending_age = {
                p.name: self._pending_age.get(p.name, 0) + 1
                for p in self._pending
            }
            pending_names = [p.name for p in self._pending]
            # Round-18: the per-reconcile FULL occupancy sweep — evictions
            # and re-placements above moved fractions around, and this is
            # the one place departed chips get their final 0.0 (the
            # submit/delete paths only refresh the nodes they touch)
            self._update_occupancy_gauges()
        return {
            "failed_nodes": failed,
            "suspect_nodes": sorted(suspect),
            "rescheduled": rescheduled,
            "pending": pending_names,
            "reserved_gang": reservation["gang"] if reservation else None,
        }

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            if self.draining:
                # a draining control plane is FROZEN end to end: client
                # mutations 503 AND the reconcile loop stops evicting/
                # re-placing — the operator's handoff snapshot stays put
                continue
            try:
                result = self.poll_once()
                if result["failed_nodes"] or result["rescheduled"]:
                    utils.logf(0, "reconcile: %s", result)
            except Exception as e:  # noqa: BLE001 — the loop must survive
                utils.errorf("reconcile pass failed: %s", e)

    @property
    def pending_pods(self) -> List[str]:
        with self._lock:
            return [p.name for p in self._pending]

    # -- cold-restart recovery (Round-20) ------------------------------------

    def _recover(self) -> dict:
        """Rebuild the control plane from the replayed journal, then
        reconcile it against what the agents ACTUALLY hold. Ordering:

        1. re-probe each journaled agent (its pre-crash allocation
           ledger is scraped FIRST — the diff baseline must be what the
           agent believed before we start re-allocating);
        2. re-pin journaled placements through the NORMAL scheduler
           (``schedule(pod, node_filter)``) — a placement whose node
           didn't return or no longer fits is a ghost and re-enters the
           pending queue like any evicted pod;
        3. re-run the wire allocations for restored placements (launcher
           env re-derivable; failures roll back to pending via the
           shared ``_allocate_batch``);
        4. free agent-ledger ORPHANS — pods an agent still holds that no
           surviving placement explains;
        5. re-apply operator cordons (AFTER placement: a cordon keeps
           its pods, it only blocks new ones);
        6. gate on ``check_invariants()`` — only a clean cluster opens
           the wire (``recovering`` flips false); a dirty one raises and
           leaves mutations refused.

        Every diff surfaces as a ``kubetpu_recovery_*`` counter and an
        event; the wall-clock cost lands in
        ``kubetpu_recovery_last_replay_seconds``."""
        from kubetpu.wire.client import probe_remote_agent

        state = self._recovered_state or {}
        t0 = time.monotonic()
        self.registry.counter("kubetpu_recovery_replays_total").inc()
        reachable: Dict[str, tuple] = {}
        agent_allocs: Dict[str, set] = {}
        for name, info in sorted(state.get("agents", {}).items()):
            url, tok = info["url"], info.get("token")
            try:
                dev, ninfo = probe_remote_agent(
                    url, name=name, token=tok, retry=self.agent_retry)
            except Exception as e:  # noqa: BLE001 — a dark agent's pods
                # fall to pending below; the agent re-registers itself
                # (or the operator does) when it returns
                self.registry.counter(
                    "kubetpu_recovery_agents_unreachable_total").inc()
                self.events.emit("recovery_agent_unreachable",
                                 node=name, url=url, error=str(e))
                continue
            try:
                body = json.loads(request_text(
                    url + "/allocations", token=tok, timeout=5.0,
                    retry=NO_RETRY))
                agent_allocs[name] = set(body.get("allocations", {}))
            except Exception:  # noqa: BLE001 — pre-ledger agents have
                agent_allocs[name] = set()  # nothing to reconcile
            with self._lock:
                self.cluster.register_node(
                    ninfo.name, device=dev, node_info=ninfo, probe=False)
                self._node_urls[ninfo.name] = url
                self._health[ninfo.name] = NodeHealth()
            self.events.emit("recovery_agent", node=name, url=url)
            reachable[name] = (url, tok)
        restored: List = []
        with self._lock:
            # gang ids must not collide with replayed stamps
            self.cluster._gang_seq = max(
                self.cluster._gang_seq, int(state.get("gang_seq", 0)))
            for pname, pl in sorted(state.get("placements", {}).items()):
                pod = pod_info_from_json(pl["pod"])
                node = pl["node"]
                try:
                    if node not in self.cluster.nodes:
                        raise SchedulingError(
                            f"node {node!r} did not return")
                    placed = self.cluster.schedule(
                        pod, lambda n, node=node: n == node)
                    restored.append(placed)
                    self.registry.counter(
                        "kubetpu_recovery_placements_restored_total").inc()
                except SchedulingError as e:
                    # ghost placement: journaled but unrealizable — back
                    # through the pending queue, the normal path
                    self.registry.counter(
                        "kubetpu_recovery_ghosts_repended_total").inc()
                    self.events.emit("recovery_ghost_pod", pod=pname,
                                     node=node, error=str(e))
                    self._pending.append(pod)
            for pj in state.get("pending", []):
                pod = pod_info_from_json(pj)
                if not self._pod_name_in_use(pod.name):
                    self._pending.append(pod)
            snapshots = [
                (_reset_for_reschedule(p), p,
                 *self._snapshot_placed(p.name, p.node_name))
                for p in restored
            ]
        self._allocate_batch(snapshots)
        # orphans: agent-ledger pods no surviving placement explains
        with self._lock:
            orphans = []
            for node, pods in sorted(agent_allocs.items()):
                held = self.cluster.nodes.get(node)
                mine = set(held.pods) if held is not None else set()
                orphans.extend((node, p) for p in sorted(pods - mine))
        for node, pname in orphans:
            url, tok = reachable[node]
            try:
                # deliberately unkeyed single attempt: a failed free is
                # re-diffed (and re-freed) by the next cold restart
                # ktlint: disable=KTP002
                request_json(url + "/release", {"pod": pname},
                             token=tok, timeout=5.0, retry=NO_RETRY)
                self.registry.counter(
                    "kubetpu_recovery_orphans_freed_total").inc()
                self.events.emit("recovery_orphan_freed", node=node,
                                 pod=pname)
            except Exception as e:  # noqa: BLE001 — retried next restart
                self.events.emit("recovery_release_failed", node=node,
                                 pod=pname, error=str(e))
        with self._lock:
            for name in state.get("cordons", []):
                if name in self.cluster.nodes:
                    self.cluster.cordon(name)
            problems = self.cluster.check_invariants()
            pending_n = len(self._pending)
        if problems:
            self.events.emit("recovery_invariants_failed",
                             problems=problems[:5])
            raise RuntimeError(
                f"recovery reconciliation left a dirty cluster; the "
                f"wire stays closed to mutations: {problems[:5]}")
        # true-up the journal to the reconciled state: a second restart
        # replays this snapshot instead of the pre-crash WAL
        if self.journal is not None:
            with self._lock:
                snap = self._journal_state_locked()
            self.journal.snapshot(snap)
        dt = time.monotonic() - t0
        self.registry.gauge(
            "kubetpu_recovery_last_replay_seconds",
            "wall-clock cost of the last journal replay + "
            "reconciliation").set(dt)
        self.recovering = False
        out = {"agents": len(reachable), "placements": len(restored),
               "pending": pending_n, "orphans_freed": len(orphans),
               "seconds": round(dt, 4)}
        self.events.emit("recovered", **out)
        utils.logf(0, "recovered: %s", out)
        return out

    # -- lifecycle -----------------------------------------------------------

    def drain_server(self) -> None:
        """Freeze the control plane for a handoff: mutating work is
        refused 503 (reads keep answering, ``/healthz`` reports
        ``draining``), in-flight requests finish, and the background
        reconcile loop pauses — no eviction or re-placement moves pods
        out from under the operator. Named apart from the node-drain
        route (``_drain``)."""
        if not self.draining:
            self.events.emit("controller_drain")
        self.draining = True

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> str:
        # the wire opens FIRST so liveness probes can watch the
        # "recovering" flag, but mutations answer 503 until _recover()
        # reconciles and check_invariants passes; only then does the
        # reconcile loop start moving pods
        threading.Thread(
            target=self._httpd.serve_forever, name="kubetpu-controller",
            daemon=True,
        ).start()
        if self.recovering:
            self._recover()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="kubetpu-reconcile", daemon=True
        )
        self._poll_thread.start()
        return self.address

    def wait(self) -> None:
        """Block until shutdown (the CLI's serve-forever)."""
        if self._poll_thread is not None:
            self._poll_thread.join()

    def shutdown(self, graceful: bool = True, timeout: float = 5.0) -> None:
        """Stop the daemon. ``graceful`` first refuses new mutating work
        and waits (bounded) for in-flight requests to finish — no response
        is cut mid-write; set False to simulate abrupt death."""
        self._stop.set()
        if graceful:
            self.draining = True
            self._inflight.wait_idle(timeout)
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=self.poll_interval + 5)
        if self.journal is not None:
            # every append already flushed before its ack — closing the
            # handle loses nothing even on the abrupt path
            self.journal.close()


def pod_to_json(pod) -> dict:
    """Convenience re-export for API clients building /pods bodies."""
    return pod_info_to_json(pod)
