"""``RemoteDevice`` — a ``device.Device`` whose node lives in another
process, reached over the agent HTTP wire.

The control-plane counterpart of ``NodeAgentServer``: ``update_node_info``
becomes ``GET /nodeinfo`` and ``allocate`` becomes ``POST /allocate``, so a
``Cluster`` registers a live agent exactly like an in-process manager —
``refresh_node`` polls the wire, ``Cluster.allocate`` calls through it. A
dead agent raises ``AgentUnreachable``; ``Cluster.poll_remote_nodes`` turns
that into the ``fail_node`` -> reschedule path (SURVEY.md §5.3).

Follows the reference's HTTP-backend pattern (``NvidiaDockerPlugin``'s REST
client against localhost:3476, ``nvidia_docker_plugin.go:21-27``) with
stdlib urllib — no third-party HTTP dependency.

Chaos-hardening contract (shared ``request_json`` discipline):

- every wire call runs under jittered exponential retry with a per-call
  deadline (``retry=`` — a transient blip costs a backoff, not a node
  eviction); ``AgentUnreachable`` now means "unreachable after the whole
  retry budget";
- ``POST /allocate`` carries a client-generated idempotency key, fresh
  per LOGICAL call and shared across its retries, so a retried allocate
  whose first response was lost mid-flight is replayed from the agent's
  dedup window instead of double-allocating.
"""

from __future__ import annotations

import json
import urllib.error
import uuid
from typing import Optional

from kubetpu.api.device import AllocateResult, Device
from kubetpu.api.types import ContainerInfo, NodeInfo, PodInfo
from kubetpu.wire.codec import (
    allocate_result_from_json,
    node_info_from_json,
    pod_info_to_json,
)
from kubetpu.wire.httpcommon import (
    TRANSIENT_ERRORS,
    RetryPolicy,
    request_json,
)


class AgentUnreachable(ConnectionError):
    """The node agent did not answer — treat the node as failed."""


# agent calls: tight per-attempt timeout, small budget — the controller's
# probe pool must converge within one reconcile pass, not block it
AGENT_RETRY = RetryPolicy(
    attempts=3, base_delay=0.05, max_delay=0.5, deadline=12.0
)


def probe_remote_agent(
    url: str,
    name: Optional[str] = None,
    token: Optional[str] = None,
    retry: Optional[RetryPolicy] = None,
):
    """Health-check + probe an agent and return ``(RemoteDevice, NodeInfo)``
    — the wire half of remote-node registration, factored out so callers
    that serialize cluster mutations under a lock (the controller) can keep
    this slow leg OUTSIDE it. Raises ``AgentUnreachable``/``ValueError``."""
    from kubetpu.api.types import new_node_info

    dev = RemoteDevice(url, token=token, retry=retry)
    dev.start()  # fail fast on a dead address
    info = new_node_info(name or "")
    dev.update_node_info(info)
    if not info.name:
        raise ValueError(f"agent at {url} advertises no node name; pass name=")
    return dev, info


class RemoteDevice(Device):
    """Device manager proxy over a node agent's HTTP surface."""

    def __init__(
        self,
        url: str,
        timeout: float = 5.0,
        token: Optional[str] = None,
        retry: Optional[RetryPolicy] = None,
        faults=None,
    ) -> None:
        """*token*: shared-secret bearer token matching the agent's
        (``NodeAgentServer(token=)`` / agent ``KUBETPU_WIRE_TOKEN``);
        defaults to the client-side ``KUBETPU_WIRE_TOKEN`` env.
        *retry*: per-call retry/backoff budget (default ``AGENT_RETRY``).
        *faults*: a ``FaultInjector`` for this client's outbound calls
        (chaos tests); None also consults the process-wide injector."""
        import os

        self.url = url.rstrip("/")
        self.timeout = timeout
        if token is None:
            token = os.environ.get("KUBETPU_WIRE_TOKEN")
        self.token = token or None  # "" (blank env var) = no auth, both sides
        self.retry = retry or AGENT_RETRY
        self.faults = faults
        self._plugin_name: Optional[str] = None

    # -- transport ----------------------------------------------------------

    def _request(
        self,
        path: str,
        payload: Optional[dict] = None,
        idempotency_key: Optional[str] = None,
    ) -> dict:
        try:
            return request_json(
                self.url + path,
                payload,
                token=self.token,
                timeout=self.timeout,
                retry=self.retry,
                idempotency_key=idempotency_key,
                faults=self.faults,
            )
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read()).get("error", str(e))
            except Exception:  # noqa: BLE001
                detail = str(e)
            if e.code in (502, 503, 504):
                # infra-transient through the whole retry budget (agent
                # draining, injected faults, idempotent dup in flight):
                # the node is effectively unreachable right now — let the
                # caller's breaker/reconcile logic absorb it
                raise AgentUnreachable(
                    f"agent {self.url}{path}: {detail}"
                ) from e
            # The agent answered with an application error — surface it as a
            # normal failure, NOT as node death.
            raise RuntimeError(f"agent {self.url}{path}: {detail}") from e
        except TRANSIENT_ERRORS as e:
            raise AgentUnreachable(f"agent {self.url} unreachable: {e}") from e

    # -- Device surface ------------------------------------------------------

    def new(self) -> None:
        """Nothing to initialize locally; state lives in the agent."""

    def start(self) -> None:
        """Health-check the agent (raises AgentUnreachable if down)."""
        health = self._request("/healthz")
        self._plugin_name = health.get("plugin")

    def update_node_info(self, node_info: NodeInfo) -> None:
        remote = node_info_from_json(self._request("/nodeinfo"))
        node_info.capacity = remote.capacity
        node_info.allocatable = remote.allocatable
        node_info.kube_cap = remote.kube_cap
        node_info.kube_alloc = remote.kube_alloc
        if not node_info.name:
            node_info.name = remote.name

    def allocate(self, pod: PodInfo, container: ContainerInfo) -> AllocateResult:
        cname = next(
            (
                n
                for n, c in list(pod.running_containers.items())
                + list(pod.init_containers.items())
                if c is container
            ),
            None,
        )
        if cname is None:
            raise ValueError("container is not part of pod")
        # one key per LOGICAL allocate, shared by its retries: the agent's
        # dedup window replays a lost response instead of re-allocating
        result = self._request(
            "/allocate",
            {"pod": pod_info_to_json(pod), "container": cname},
            idempotency_key=uuid.uuid4().hex,
        )
        return allocate_result_from_json(result)

    def get_name(self) -> str:
        return self._plugin_name or "remote"
