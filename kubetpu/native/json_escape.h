// Minimal JSON string escaper shared by the native probes (tpuinfo,
// gpuinfo): quote, backslash, control chars, and EVERY byte >= 0x7f.
// Sysfs fixtures feed arbitrary bytes into string fields; a raw quote
// would break the JSON framing, and a stray non-UTF-8 byte (0xFF in a
// fixture file) would make the Python json parser reject the whole
// document. \u00XX-escaping all non-ASCII keeps the output parseable
// bytes-for-bytes (multibyte UTF-8 arrives latin-1-mangled, which is the
// right trade for a hardware prober: diagnostics stay readable, framing
// never breaks).
#ifndef KUBETPU_NATIVE_JSON_ESCAPE_H_
#define KUBETPU_NATIVE_JSON_ESCAPE_H_

#include <cstdio>
#include <string>

namespace kubetpu {

inline std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\b': out += "\\b"; break;
      case '\f': out += "\\f"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20 || c >= 0x7f) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += static_cast<char>(c);
        }
    }
  }
  return out;
}

}  // namespace kubetpu

#endif  // KUBETPU_NATIVE_JSON_ESCAPE_H_
