"""``Journal`` — the controller's durable write-ahead log (Round-20).

The scheduler is only as trustworthy as the cluster state it scores
against, and until this round that state lived purely in controller
memory: a SIGKILL stranded every agent-held allocation and forgot every
placement, milli binding and pending pod. This module is the crash
layer's foundation — an append-only, checksummed, torn-tail-tolerant
JSONL WAL plus an atomically-replaced snapshot:

- **Record format**: one JSON object per line,
  ``{"seq": N, "kind": K, "data": {...}, "crc": C}`` where ``crc`` is
  the CRC-32 of the canonical (sorted-key, tight-separator) encoding of
  ``[seq, kind, data]``. The checksum makes torn writes and bit rot
  DETECTABLE; canonical encoding makes it stable across writers.
- **Torn tail**: a crash mid-``append`` can leave a partial or
  corrupt LAST line. ``Journal`` REPAIRS it at init — the file is
  truncated back to the last trusted newline-terminated record (counted
  in ``torn_tail_dropped``) before any new append, so a post-restart
  record can never merge onto the fragment and be lost with it; a torn
  tail is the expected signature of the very crash this journal exists
  to survive. ``replay()`` additionally drops an unrepaired tail (a
  read-only replay of a foreign WAL). A corrupt record anywhere ELSE is
  real damage and raises ``JournalCorrupt``: silently skipping mid-file
  records would replay a state that never existed.
- **Snapshot + compaction**: ``snapshot(state)`` writes
  ``<path>.snap`` via tmp + ``os.replace`` (atomic: readers see the old
  complete snapshot or the new complete one, never a torn half), THEN
  truncates the WAL. A crash between the two steps is safe because
  replay skips WAL records with ``seq <= snapshot.seq`` — re-applying
  the compaction is idempotent. The snapshot carries its own CRC.
- **Replay**: ``replay()`` returns ``(snapshot_state, records)`` —
  the caller reduces them into live state. Replaying the same journal
  twice yields the same result (no side effects in this module).

Durability is ``flush`` by default (the OS has the bytes — survives
process SIGKILL, the failure mode this round models); pass
``fsync=True`` for power-loss durability at a per-append ``fsync``
cost. All files are created owner-only (0600) — journaled
``node_register`` records and snapshots carry agent bearer tokens, and
the journal must not become a world-readable credential artifact.
Stdlib only; one writer per path (the controller serializes appends
under its own lock, and this module adds a lock of its own so journal
stats never tear).
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple


class JournalCorrupt(Exception):
    """A checksum/parse failure NOT at the tail — the journal holds
    records that cannot be trusted and replay must not guess."""


def _canonical(seq: int, kind: str, data: dict) -> bytes:
    return json.dumps([seq, kind, data], sort_keys=True,
                      separators=(",", ":")).encode()


def _crc(seq: int, kind: str, data: dict) -> int:
    return zlib.crc32(_canonical(seq, kind, data)) & 0xFFFFFFFF


class Journal:
    """Append-only WAL + snapshot for one controller's durable state."""

    def __init__(self, path: str, fsync: bool = False) -> None:
        self.path = path
        self.snap_path = path + ".snap"
        self.fsync = fsync
        self._lock = threading.Lock()
        self._fh = None
        # stats surfaced by the controller's recovery gauges
        self.records_appended = 0
        self.bytes_appended = 0
        self.torn_tail_dropped = 0
        self.snapshots_written = 0
        # journal files carry agent bearer tokens — pre-existing files
        # (created by an older writer, or with a looser umask) are
        # tightened to owner-only; new ones are born 0600 in _open_private
        for p in (self.path, self.snap_path):
            try:
                os.chmod(p, 0o600)
            except OSError:
                pass
        # repair a torn tail BEFORE the first append: a crash mid-append
        # leaves a partial last line, and appending onto it would merge
        # two records into one corrupt line — losing an acked op
        self._repair_tail()
        # resume the sequence where the existing journal left off — an
        # append after restart must never reuse a seq (replay orders and
        # dedups by it)
        self._seq = self._scan_last_seq()

    # -- write side ----------------------------------------------------------

    @staticmethod
    def _open_private(path: str, append: bool):
        """Open *path* for writing, created owner-only (0600): the WAL
        and snapshot carry agent bearer tokens and must never be born
        world-readable. ``append=False`` truncates."""
        flags = os.O_WRONLY | os.O_CREAT | (
            os.O_APPEND if append else os.O_TRUNC)
        fd = os.open(path, flags, 0o600)
        return os.fdopen(fd, "a" if append else "w", encoding="utf-8")

    def _open(self):
        if self._fh is None:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            # only ever called from append(), inside `with self._lock:`
            # — the lazy open shares append's critical section
            # ktlint: disable=KTP003
            self._fh = self._open_private(self.path, append=True)
        return self._fh

    def _repair_tail(self) -> None:
        """Truncate the WAL to the end of its last trusted,
        newline-terminated record — run once at init, BEFORE any append.
        A crash mid-append leaves a partial last line; without this
        repair the next append would land ON that fragment, merging two
        records into one corrupt line: the acked post-crash record is
        then lost at the next replay (the merged line reads as a torn
        tail), and a second such append turns it into mid-file
        corruption that refuses to boot. Only a *tail* is repaired — a
        bad line with a trusted record after it is real damage, left in
        place for replay to raise ``JournalCorrupt`` on rather than
        guessed away here. A final record that is valid but missing its
        terminator (the crash hit between the JSON and the newline) is
        an acked op: it gets its newline instead of being dropped."""
        try:
            fh = open(self.path, "r+b")
        except OSError:
            return
        with fh:
            data = fh.read()
            if not data:
                return
            pos = 0          # byte offset of the current line's start
            good = 0         # offset just past the last trusted record
            tail_bad = False  # an untrusted line pending as torn-tail
            for line in data.splitlines(keepends=True):
                end = pos + len(line)
                text = line.decode("utf-8", "replace")
                if tail_bad:
                    if text.strip():
                        # trusted-or-not content AFTER a bad line: this
                        # is not a torn tail — leave the file for replay
                        # to judge (JournalCorrupt, never a guess)
                        return
                elif not text.strip():
                    good = end
                elif self._parse(text) is None:
                    tail_bad = True
                elif line.endswith(b"\n"):
                    good = end
                else:
                    # valid record, missing only its newline: terminate
                    # it so the next append starts a fresh line
                    fh.seek(0, os.SEEK_END)
                    fh.write(b"\n")
                    fh.flush()
                    if self.fsync:
                        os.fsync(fh.fileno())
                    return
                pos = end
            if tail_bad and good < len(data):
                fh.truncate(good)
                fh.flush()
                if self.fsync:
                    os.fsync(fh.fileno())
                with self._lock:
                    self.torn_tail_dropped += 1

    def append(self, kind: str, data: Optional[dict] = None) -> int:
        """Durably record one state-mutating op; returns its seq. The
        record is flushed (and optionally fsynced) before this returns —
        the controller calls this BEFORE acking the client, so an acked
        op is never lost to a SIGKILL."""
        data = data or {}
        with self._lock:
            self._seq += 1
            seq = self._seq
            line = json.dumps(
                {"seq": seq, "kind": kind, "data": data,
                 "crc": _crc(seq, kind, data)},
                sort_keys=True, separators=(",", ":")) + "\n"
            fh = self._open()
            fh.write(line)
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())
            self.records_appended += 1
            self.bytes_appended += len(line)
        return seq

    def snapshot(self, state: dict) -> int:
        """Persist *state* as the new recovery baseline and compact the
        WAL. Atomic: tmp + ``os.replace`` for the snapshot, then WAL
        truncation; a crash between the two replays the (now-redundant)
        WAL records onto the snapshot idempotently because replay skips
        ``seq <= snapshot.seq``."""
        with self._lock:
            seq = self._seq
            body = {"seq": seq, "state": state,
                    "crc": _crc(seq, "snapshot", state)}
            tmp = self.snap_path + ".tmp"
            d = os.path.dirname(os.path.abspath(self.snap_path))
            os.makedirs(d, exist_ok=True)
            with self._open_private(tmp, append=False) as fh:
                json.dump(body, fh, sort_keys=True, separators=(",", ":"))
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.snap_path)
            # WAL truncation AFTER the snapshot landed: the baseline must
            # exist before the records folded into it disappear
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            with self._open_private(self.path, append=False):
                pass
            self.snapshots_written += 1
        return seq

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # -- read side -----------------------------------------------------------

    def _scan_last_seq(self) -> int:
        """Highest trusted seq across snapshot + WAL (tolerating a torn
        tail) — where appends resume after a restart."""
        last = 0
        snap = self._read_snapshot()
        if snap is not None:
            last = snap[0]
        for rec in self._iter_wal(count_torn=False):
            last = max(last, rec["seq"])
        return last

    def _read_snapshot(self) -> Optional[Tuple[int, dict]]:
        try:
            with open(self.snap_path, "r", encoding="utf-8") as fh:
                body = json.load(fh)
        except FileNotFoundError:
            return None
        except (ValueError, OSError) as e:
            # the snapshot is written atomically (tmp + replace): a torn
            # one cannot happen by crash, only by external damage
            raise JournalCorrupt(
                f"snapshot {self.snap_path} unreadable: {e}") from e
        seq = int(body.get("seq", 0))
        state = body.get("state", {})
        if body.get("crc") != _crc(seq, "snapshot", state):
            raise JournalCorrupt(
                f"snapshot {self.snap_path} failed its checksum")
        return seq, state

    def _iter_wal(self, count_torn: bool = True) -> Iterator[dict]:
        """Yield trusted WAL records in file order. A bad LAST line is a
        torn tail (dropped, counted); a bad line with trusted records
        AFTER it is corruption and raises."""
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                lines = fh.readlines()
        except FileNotFoundError:
            return
        pending_bad: Optional[str] = None
        for line in lines:
            if not line.strip():
                continue
            rec = self._parse(line)
            if rec is None:
                if pending_bad is not None:
                    raise JournalCorrupt(
                        f"{self.path}: corrupt record mid-file "
                        f"(not a torn tail): {pending_bad[:80]!r}")
                pending_bad = line
                continue
            if pending_bad is not None:
                raise JournalCorrupt(
                    f"{self.path}: corrupt record mid-file "
                    f"(not a torn tail): {pending_bad[:80]!r}")
            yield rec
        if pending_bad is not None and count_torn:
            with self._lock:
                self.torn_tail_dropped += 1

    @staticmethod
    def _parse(line: str) -> Optional[dict]:
        try:
            rec = json.loads(line)
            seq = int(rec["seq"])
            kind = rec["kind"]
            data = rec["data"]
        except (ValueError, KeyError, TypeError):
            return None
        if rec.get("crc") != _crc(seq, kind, data):
            return None
        return {"seq": seq, "kind": kind, "data": data}

    def replay(self) -> Tuple[Dict[str, Any], List[dict]]:
        """``(snapshot_state, records)``: the compacted baseline (``{}``
        when none) plus every trusted WAL record newer than it, in seq
        order. Pure read — calling it twice yields the same result."""
        snap = self._read_snapshot()
        snap_seq, state = snap if snap is not None else (0, {})
        records = [r for r in self._iter_wal() if r["seq"] > snap_seq]
        records.sort(key=lambda r: r["seq"])
        return state, records

    def replay_state(self) -> Dict[str, Any]:
        """The reduced controller state this journal describes —
        ``replay()`` folded through ``reduce_records``. What a cold
        restart boots from."""
        state, records = self.replay()
        return reduce_records(state, records)

    def stats(self) -> dict:
        with self._lock:
            try:
                wal_bytes = os.path.getsize(self.path)
            except OSError:
                wal_bytes = 0
            return {
                "records_appended": self.records_appended,
                "bytes_appended": self.bytes_appended,
                "torn_tail_dropped": self.torn_tail_dropped,
                "snapshots_written": self.snapshots_written,
                "wal_bytes": wal_bytes,
                "seq": self._seq,
            }


# -- the reducer ------------------------------------------------------------
#
# Journal records are LOGICAL controller ops; this pure function folds
# them into the state a cold restart boots from. Keeping it here (not in
# the controller) lets the boundary tests replay a truncated WAL without
# a live control plane, and makes "replay is idempotent" a property of
# plain data: reduce(reduce(s, r), []) == reduce(s, r).


def empty_state() -> Dict[str, Any]:
    return {
        "agents": {},       # node name -> {"url": ..., "token": ...}
        "placements": {},   # pod name -> {"pod": pod_json, "node": name}
        "pending": [],      # pod_json, FIFO — queue order survives restart
        "cordons": [],      # operator cordons (health cordons re-derive)
        "gang_seq": 0,      # high-water gang id — new_gang_id must not collide
    }


def _drop_pending(state: Dict[str, Any], name: str) -> None:
    state["pending"] = [
        p for p in state["pending"] if p.get("name") != name]


def _note_gang(state: Dict[str, Any], pod_json: dict) -> None:
    gid = (pod_json.get("requests") or {}).get("kubetpu/gang")
    try:
        state["gang_seq"] = max(state["gang_seq"], int(gid))
    except (TypeError, ValueError):
        pass


def reduce_records(state: Dict[str, Any],
                   records: List[dict]) -> Dict[str, Any]:
    """Fold WAL *records* into *state* (a snapshot or ``empty_state()``).
    Mutates and returns *state*. Unknown kinds are ignored — an older
    controller replaying a newer journal degrades instead of crashing."""
    base = empty_state()
    for key, dfl in base.items():
        state.setdefault(key, dfl)
    for rec in records:
        kind, d = rec["kind"], rec["data"]
        if kind == "node_register":
            state["agents"][d["name"]] = {
                "url": d["url"], "token": d.get("token")}
        elif kind == "node_dead":
            state["agents"].pop(d["name"], None)
            # its placements fall to pending, the same motion the live
            # reconcile pass makes on a breaker eviction
            for pname in sorted(
                    n for n, pl in state["placements"].items()
                    if pl["node"] == d["name"]):
                pl = state["placements"].pop(pname)
                _drop_pending(state, pname)
                state["pending"].append(pl["pod"])
        elif kind == "pod_place":
            _drop_pending(state, d["pod"]["name"])
            state["placements"][d["pod"]["name"]] = {
                "pod": d["pod"], "node": d["node"]}
            _note_gang(state, d["pod"])
        elif kind == "pod_pending":
            name = d["pod"]["name"]
            state["placements"].pop(name, None)
            _drop_pending(state, name)
            state["pending"].append(d["pod"])
            _note_gang(state, d["pod"])
        elif kind == "pod_delete":
            state["placements"].pop(d["name"], None)
            _drop_pending(state, d["name"])
        elif kind == "cordon":
            if d.get("on", True):
                if d["name"] not in state["cordons"]:
                    state["cordons"].append(d["name"])
            else:
                state["cordons"] = [
                    c for c in state["cordons"] if c != d["name"]]
    return state
