"""The group scheduler: bin-pack a pod's topology-shaped DevRequests onto a
node's allocatable resources, filling each container's AllocateFrom map.

This is the component the reference *delegates* to the external KubeDevice
core via ``UsingGroupScheduler() == true`` (``gpu_scheduler.go:69-71``) and
never ships — its contract is pinned only by the from->to AllocateFrom shape
the device-manager test builds by hand (``nvidia_gpu_manager_test.go:38-47``:
request key -> node resource key). kubetpu implements it:

- **TPU-mesh nodes**: placement is geometric — the pod's chips are chosen
  with ``find_contiguous_block`` on the node's free torus coordinates, so
  AllocateFrom lands on an ICI-contiguous sub-slice regardless of how the
  synthetic request grouping was shaped.
- **Tree nodes (GPU)**: placement is structural — request groups map onto
  node groups best-fit (smallest sufficient group first, preserving large
  groups for later pods), devices within a group in sorted order.

Pod sizing follows the reference's counting (``gpu.go:294-303``): running
containers get *distinct* devices (sum); init containers run sequentially
before them and *reuse* the pod's device pool (max), so a pod's pool is
``max(sum(running), max(init))`` devices.

``take``/``return`` do the usage accounting (the reference core's job): the
pool's keys and the scalar resource are decremented on the node's
allocatable and restored on release.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Set, Tuple

from kubetpu.api import utils
from kubetpu.api.types import ContainerInfo, NodeInfo, PodInfo
from kubetpu.plugintypes import ResourceGPU, ResourceTPU
from kubetpu.plugintypes.mesh import find_contiguous_block
from kubetpu.scheduler import meshstate

# any 2-level cards key: captures (grp1seg, grp1id, grp0seg, grp0id, baseseg, devid)
_CARDS_KEY_RE = re.compile(
    r"^resource/group/([a-z]+grp1)/([^/]+)/([a-z]+grp0)/([^/]+)/([a-z]+)/([^/]+)/cards$"
)
# the fractional sibling (Round-18 vChips): per-chip capacity in
# milli-chips, shared by up to 1000/m fractional pods
_MILLI_KEY_RE = re.compile(
    r"^resource/group/([a-z]+grp1)/([^/]+)/([a-z]+grp0)/([^/]+)/([a-z]+)/([^/]+)/milli$"
)

_SCALAR_BY_BASE = {"tpu": ResourceTPU, "gpu": ResourceGPU}


def _cards_request_keys(cont: ContainerInfo, base: str) -> Optional[List[str]]:
    """Container's cards request keys of a base segment, sorted. Returns
    None for unsupported quantity>1 keys: AllocateFrom is a from->to map, so
    one request key can only bind one device (cards are advertised and
    translated with quantity 1; a >1 quantity would silently lose devices)."""
    out: List[str] = []
    for key, val in cont.dev_requests.items():
        m = _CARDS_KEY_RE.match(key)
        if m and m.group(5) == base:
            if val > 1:
                utils.errorf("unsupported cards request quantity %d for %s", val, key)
                return None
            out.append(key)
    return sorted(out)


def _request_bases(pod_info: PodInfo) -> Set[str]:
    bases: Set[str] = set()
    for cont in list(pod_info.init_containers.values()) + list(
        pod_info.running_containers.values()
    ):
        for key in cont.dev_requests:
            m = _CARDS_KEY_RE.match(key)
            if m:
                bases.add(m.group(5))
    return bases


def _free_node_cards(node_info: NodeInfo, base: str) -> List[str]:
    """Node's allocatable cards keys for a base segment, sorted."""
    out = []
    for key, val in node_info.allocatable.items():
        m = _CARDS_KEY_RE.match(key)
        if m and m.group(5) == base and val >= 1:
            out.append(key)
    return sorted(out)


def _pick_pool_tree(n: int, free_keys: List[str]) -> Optional[List[str]]:
    """Choose n node keys structurally: whole groups best-fit (smallest
    sufficient group first), spilling across the largest groups when no
    single group holds the remainder."""
    if n > len(free_keys):
        return None
    groups: Dict[Tuple[str, str], List[str]] = {}
    for key in free_keys:
        m = _CARDS_KEY_RE.match(key)
        assert m
        groups.setdefault((m.group(2), m.group(4)), []).append(key)
    pool: List[str] = []
    remaining = n
    avail = {g: sorted(keys) for g, keys in groups.items()}
    while remaining > 0:
        fitting = sorted(
            (g for g in avail if len(avail[g]) >= remaining),
            key=lambda g: (len(avail[g]), g),
        )
        if fitting:
            g = fitting[0]
            pool.extend(avail[g][:remaining])
            remaining = 0
        else:
            g = sorted(avail, key=lambda g: (-len(avail[g]), g))[0]
            pool.extend(avail[g])
            remaining -= len(avail[g])
            del avail[g]
    return pool


def _pick_pool_mesh(n: int, state: meshstate.NodeMeshState) -> Optional[List[str]]:
    """Choose n node keys geometrically: an ICI-contiguous block."""
    placed = find_contiguous_block(state.free, n, state.topo)
    if placed is None:
        return None
    coords, score = placed
    utils.logf(4, "geometric fill: %d chips, contiguity %.3f", n, score)
    keys: List[str] = []
    for c in coords:
        local = state.coord_chip.get(c)
        key = state.chip_key.get(local) if local is not None else None
        if key is None:
            return None
        keys.append(key)
    return sorted(keys)


def _fill_fractional(
    state: meshstate.NodeMeshState, pod_info: PodInfo, milli: int
) -> bool:
    """Bind a fractional (vChip) pod to ONE chip's ``/milli`` key,
    BEST-FIT: the fitting chip with the least remaining capacity wins
    (ties to the lowest local id), so fractional confetti concentrates
    on already-broken chips and pristine chips stay whole for future
    gangs — the anti-fragmentation policy. Every container shares the
    pod's single vChip (the pod-level request grammar); the binding is
    key -> key because the fractional grammar has no translation stage."""
    best = state.best_fit_milli(milli)
    if best is None:
        return False
    conts = list(pod_info.running_containers.values()) + list(
        pod_info.init_containers.values()
    )
    if not conts:
        # nothing to bind the share to — a container-less pod placed
        # "successfully" would hold no /milli key and corrupt the books
        return False
    _free, _local, mkey = best
    for cont in conts:
        # strip stale /milli bindings from a PREVIOUS placement first (a
        # re-scheduled pod — preemption re-pend, dead-node reconcile —
        # arrives still carrying its old chip's key; binding the new one
        # on top would make _account move the share on BOTH keys and
        # strand phantom capacity on the new node's books)
        for stale in [k for k in cont.allocate_from
                      if _MILLI_KEY_RE.match(k)]:
            del cont.allocate_from[stale]
        for stale in [k for k in cont.dev_requests
                      if _MILLI_KEY_RE.match(k)]:
            del cont.dev_requests[stale]
        cont.dev_requests[mkey] = milli
        cont.allocate_from[mkey] = mkey
    return True


def fill_allocate_from(node_info: NodeInfo, pod_info: PodInfo) -> bool:
    """Fill every container's AllocateFrom from the node's allocatable;
    all-or-nothing per pod (no partial state on failure). Fractional
    (vChip) pods take the dedicated best-fit chip binding instead of the
    grouped-cards pool walk."""
    state = meshstate.parse_mesh_state(node_info.allocatable)
    milli = meshstate.pod_milli(pod_info)
    if milli > 0:
        # a vChip needs mesh geometry (the /milli advertisement rides the
        # chip-coordinate grammar); mixing with whole-chip requests is
        # refused upstream by the schedulers' fit predicate
        if state is None:
            return False
        return _fill_fractional(state, pod_info, milli)
    running = [
        pod_info.running_containers[k]
        for k in utils.sorted_string_keys(pod_info.running_containers)
    ]
    inits = [
        pod_info.init_containers[k]
        for k in utils.sorted_string_keys(pod_info.init_containers)
    ]

    tentative: List[Tuple[ContainerInfo, str, str]] = []
    for base in sorted(_request_bases(pod_info)):
        running_reqs = []
        for cont in running:
            keys = _cards_request_keys(cont, base)
            if keys is None:
                return False
            running_reqs.extend((cont, key) for key in keys)
        init_keys = []
        for cont in inits:
            keys = _cards_request_keys(cont, base)
            if keys is None:
                return False
            init_keys.append((cont, keys))
        init_maxes = [len(keys) for _, keys in init_keys]
        pool_n = max([len(running_reqs)] + init_maxes) if (running_reqs or init_maxes) else 0
        if pool_n == 0:
            continue

        if base == "tpu" and state is not None:
            pool = _pick_pool_mesh(pool_n, state)
        else:
            pool = _pick_pool_tree(pool_n, _free_node_cards(node_info, base))
        if pool is None:
            return False

        # running containers: distinct devices from the pool, in order
        for (cont, req_key), node_key in zip(running_reqs, pool):
            tentative.append((cont, req_key, node_key))
        # init containers: run sequentially before running ones -> reuse the
        # front of the pool
        for cont, keys in init_keys:
            for req_key, node_key in zip(keys, pool):
                tentative.append((cont, req_key, node_key))

    for cont, from_key, to_key in tentative:
        cont.allocate_from[from_key] = to_key
    return True


def take_pod_resources(node_info: NodeInfo, pod_info: PodInfo) -> None:
    """Decrement the node's allocatable by the pod's held pool (running
    containers; init containers reuse it) — the accounting the external
    core performed for the reference."""
    _account(node_info, pod_info, sign=-1)


def return_pod_resources(node_info: NodeInfo, pod_info: PodInfo) -> None:
    _account(node_info, pod_info, sign=+1)


def _pod_held_keys(pod_info: PodInfo) -> Set[str]:
    held: Set[str] = set()
    for cont in pod_info.running_containers.values():
        held.update(cont.allocate_from.values())
    for cont in pod_info.init_containers.values():
        held.update(cont.allocate_from.values())  # usually a subset
    return held


def held_cards(pod_info: PodInfo, base: str) -> Set[str]:
    """The node cards keys of *base* a placed pod holds (its device pool) —
    input to cross-class preemption/defrag victim selection."""
    out: Set[str] = set()
    for key in _pod_held_keys(pod_info):
        m = _CARDS_KEY_RE.match(key)
        if m and m.group(5) == base:
            out.add(key)
    return out


def held_milli(pod_info: PodInfo) -> Dict[str, int]:
    """The fractional holds of a placed pod as milli-key -> milli-chips
    (at most one entry today: a pod carries one vChip). Input to the
    Round-18 packing oracle and fractional preemption."""
    out: Dict[str, int] = {}
    milli = meshstate.pod_milli(pod_info)
    if not milli:
        return out
    for key in _pod_held_keys(pod_info):
        if _MILLI_KEY_RE.match(key):
            out[key] = milli
    return out


def free_cards_by_group(node_info: NodeInfo, base: str) -> Dict[str, List[str]]:
    """Free cards keys of *base* grouped by their level-1 group id — the
    structural-fill view of a tree node's fragmentation (NVLink locality:
    the reference's gpugrp1 is the socket level, nvidia_gpu_manager.go
    :74-88)."""
    groups: Dict[str, List[str]] = {}
    for key, val in node_info.allocatable.items():
        m = _CARDS_KEY_RE.match(key)
        if m and m.group(5) == base and val >= 1:
            groups.setdefault(m.group(2), []).append(key)
    return {g: sorted(keys) for g, keys in groups.items()}


def cards_group(key: str) -> Optional[str]:
    """Level-1 group id of a cards key, or None if it isn't one."""
    m = _CARDS_KEY_RE.match(key)
    return m.group(2) if m else None


def _account(node_info: NodeInfo, pod_info: PodInfo, sign: int) -> None:
    # the one in-place mutator of advertised ResourceLists: drop any
    # memoized mesh geometry for this dict (meshstate memo contract)
    meshstate.invalidate_mesh_state(node_info.allocatable)
    for to_key in _pod_held_keys(pod_info):
        m = _CARDS_KEY_RE.match(to_key)
        if not m:
            if _MILLI_KEY_RE.match(to_key):
                # fractional hold: the pod's vChip share moves on the
                # chip's milli key; the scalar whole-chip tally is
                # untouched (the chip's cards key stays advertised — it
                # is the mesh-state parse that hides a partially-
                # occupied chip from whole-chip placement)
                node_info.allocatable[to_key] = (
                    node_info.allocatable.get(to_key, 0)
                    + sign * meshstate.pod_milli(pod_info)
                )
            continue
        node_info.allocatable[to_key] = node_info.allocatable.get(to_key, 0) + sign
        scalar = _SCALAR_BY_BASE.get(m.group(5))
        if scalar is not None:
            node_info.allocatable[scalar] = node_info.allocatable.get(scalar, 0) + sign
            node_info.kube_alloc[scalar] = node_info.kube_alloc.get(scalar, 0) + sign
