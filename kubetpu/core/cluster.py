"""The cluster scheduling harness — kubetpu's stand-in for the external
KubeDevice core the reference depends on but does not ship (SURVEY.md §7
step 6): node registry, the per-pod predicate/score/allocate loop, the
group-scheduler fill, usage accounting, and gang (all-or-nothing)
scheduling for multi-host slices.

Flow per pod (mirrors the reference's documented call stack, SURVEY.md §3.3):

    schedule(pod)
      for each node: plugin.pod_fits_device(node, pod') -> (fits, _, score)
      pick best (score, then node name — node names sort hosts in slice
        order, so equal-score gang members fill contiguous host blocks)
      plugin.pod_allocate(node, pod')           # re-translate on the winner
      group_scheduler.fill_allocate_from        # geometric / structural fill
      group_scheduler.take_pod_resources        # accounting
      device.allocate(pod, container)           # at container start (CRI)
"""

from __future__ import annotations

import heapq
import itertools
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from kubetpu.api import utils
from kubetpu.api.device import AllocateResult, Device
from kubetpu.api.devicescheduler import DeviceScheduler
from kubetpu.api.types import NodeInfo, PodInfo, new_node_info
from kubetpu.core import group_scheduler
from kubetpu.core.metrics import LatencyRecorder
from kubetpu.plugintypes import ResourceGPU, ResourceTPU
from kubetpu.plugintypes.mesh import (
    TpuTopology,
    contiguity_score,
    enumerate_blocks,
    factorizations,
    find_contiguous_block,
    find_perfect_block,
    host_block_links,
)
from kubetpu.scheduler import meshstate
from kubetpu.scheduler.deviceclass import GPU, TPU
from kubetpu.scheduler.fitindex import FitIndex
from kubetpu.scheduler.gpu_scheduler import GpuScheduler
from kubetpu.scheduler.tpu_scheduler import TpuScheduler
from kubetpu.scheduler.translate import (
    pod_device_count,
    pod_device_need,
    pod_wants_device,
)


class SchedulingError(Exception):
    """Pod (or gang) cannot be placed."""


# cross-check sentinel: "no reference computed" must be distinguishable
# from "reference found no fit" (None is a legitimate reference value)
_NO_REFERENCE = object()


# Pod priority pseudo-resource (rides Requests untouched, like the
# topology-generation knob); higher preempts lower via schedule_preempting.
PriorityKey = "kubetpu/priority"

# Gang identity pseudo-resource: schedule_gang stamps every member with one
# id, so later RE-placements (reconcile after a node death) can honor the
# single-slice invariant toward the gang's surviving members — an untagged
# individual reschedule would silently straddle slices over DCN.
GangKey = "kubetpu/gang"


def pod_priority(pod: PodInfo) -> int:
    return int(pod.requests.get(PriorityKey, 0))


@dataclass
class ClusterNode:
    info: NodeInfo
    device: Optional[Device] = None
    pods: Dict[str, PodInfo] = field(default_factory=dict)


@dataclass(frozen=True)
class Migration:
    """One step of a defragmentation plan."""

    pod_name: str
    from_node: str
    to_node: str


def _reset_for_reschedule(pod: PodInfo) -> PodInfo:
    """A schedulable copy of a placed pod: placement artifacts stripped so
    it can go back through the full schedule path."""
    fresh = pod.copy()
    fresh.node_name = ""
    for cont in list(fresh.init_containers.values()) + list(
        fresh.running_containers.values()
    ):
        cont.allocate_from.clear()
        cont.dev_requests.clear()
    return fresh


class Cluster:
    """Node registry + scheduling loop over the device-scheduler plugins."""

    # ring buffer size of the event log (observability; SURVEY.md §5.1/5.5)
    MAX_EVENTS = 1000

    def __init__(
        self,
        schedulers: Optional[Sequence[DeviceScheduler]] = None,
        use_fit_index: Optional[bool] = None,
    ):
        self.schedulers: List[DeviceScheduler] = (
            list(schedulers) if schedulers is not None else [TpuScheduler(), GpuScheduler()]
        )
        self.nodes: Dict[str, ClusterNode] = {}
        self.cordoned: set = set()  # unschedulable (maintenance) nodes
        self.metrics = LatencyRecorder()
        self.events: List[Dict[str, object]] = []
        self._gang_seq = 0  # gang-identity stamps (GangKey)
        # Round-21 incremental fit index (scheduler/fitindex.py): prunes
        # the O(fleet) predicate sweep to a provably-equivalent candidate
        # list. KUBETPU_NO_FIT_INDEX=1 is the operator kill switch back to
        # the pure sweep (and the A/B lever for the equivalence tests).
        if use_fit_index is None:
            use_fit_index = not os.environ.get("KUBETPU_NO_FIT_INDEX")
        self.use_fit_index: bool = use_fit_index
        self.fit_index = FitIndex()
        # The frac fast path (FitIndex.frac_ordered) hands the sweep exact
        # per-candidate scores, which is only sound when every scheduler's
        # contribution for a pure-vChip pod is the stock one (Tpu scores
        # the remainder fit, Gpu contributes 0.0). Custom scheduler sets
        # fall back to the unordered eligible-set prune.
        self._caps_ok: bool = all(
            type(s) in (TpuScheduler, GpuScheduler) for s in self.schedulers
        )
        # Cross-check oracle (sched_check / property tests): every pruned
        # sweep is shadowed by a reference full sweep and any divergence
        # in (first node tried, score) raises — NOT for production paths.
        self.index_cross_check: bool = False
        self.index_stats: Dict[str, int] = {
            "pruned_sweeps": 0, "fallback_sweeps": 0, "cross_checks": 0,
        }
        # O(1) pod -> node map (release/allocate used to scan the fleet);
        # audited against node.pods by check_invariants.
        self._pod_node: Dict[str, str] = {}
        # Nodes whose advertised books changed since the controller last
        # drained this set — the incremental occupancy-gauge feed.
        self._occ_dirty: Set[str] = set()
        # name -> the allocatable dict currently carrying its dirty hook
        # (lifecycle paths replace the dict object; we must re-hook).
        self._hooked_alloc: Dict[str, dict] = {}
        self._names_cache: Optional[List[str]] = None
        self._slices_cache: Optional[Dict[str, List[str]]] = None

    def _event(self, kind: str, **detail: object) -> None:
        self.events.append({"ts": time.time(), "kind": kind, **detail})
        if len(self.events) > self.MAX_EVENTS:
            del self.events[: len(self.events) - self.MAX_EVENTS]

    # -- node lifecycle -----------------------------------------------------

    def register_node(
        self,
        name: str,
        device: Optional[Device] = None,
        node_info: Optional[NodeInfo] = None,
        probe: bool = True,
    ) -> NodeInfo:
        """Register a node from its device manager's advertisement (or a
        prebuilt NodeInfo), and AddNode it into every scheduler plugin.
        ``probe=False`` skips the device probe when *node_info* already holds
        a fresh advertisement (avoids a duplicate wire round-trip)."""
        info = node_info if node_info is not None else new_node_info(name)
        info.name = name
        if device is not None and probe:
            device.update_node_info(info)
        for s in self.schedulers:
            s.add_node(name, info)
        self.nodes[name] = ClusterNode(info=info, device=device)
        self._index_register(name)
        return info

    def _index_register(self, name: str) -> None:
        """(Re)attach the node's fit-index entry and dirty hook to its
        CURRENT allocatable dict. Lifecycle paths (register/refresh)
        replace the dict object — the mutation choke point
        (meshstate.invalidate_mesh_state) keys hooks on dict identity, so
        each replacement must re-hook here; in-place accounting mutations
        between lifecycle events are covered by the hook itself."""
        node = self.nodes[name]
        alloc = node.info.allocatable
        old = self._hooked_alloc.get(name)
        if old is not None and old is not alloc:
            meshstate.unregister_dirty_hook(old)
        meshstate.register_dirty_hook(alloc, self._mark_node_dirty, name)
        self._hooked_alloc[name] = alloc
        self.fit_index.register(name, alloc)
        self._occ_dirty.add(name)
        self._names_cache = None
        self._slices_cache = None

    def _mark_node_dirty(self, name: str) -> None:
        """Dirty-hook body: accounting mutated this node's books. Must
        stay O(1) — it fires inside _account, mid-mutation."""
        self.fit_index.mark_dirty(name)
        self._occ_dirty.add(name)

    def _index_alloc(self, name: str):
        """Ground-truth resolver for lazy index refresh."""
        node = self.nodes.get(name)
        return None if node is None else node.info.allocatable

    def _sorted_names(self) -> List[str]:
        """Sorted node names, cached between node add/remove — rebuilding
        (and re-sorting) the fleet list per pod was measurable at 4096
        chips even when the index pruned the sweep itself."""
        if self._names_cache is None:
            self._names_cache = utils.sorted_string_keys(self.nodes)
        return self._names_cache

    def pop_dirty_occupancy(self) -> Set[str]:
        """Drain the set of nodes whose books changed since the last call
        (includes removed nodes) — the controller's incremental
        occupancy-gauge feed."""
        dirty = self._occ_dirty
        self._occ_dirty = set()
        return dirty

    def remove_node(self, name: str) -> None:
        node = self.nodes.get(name)
        if node is not None:
            for pname in node.pods:
                self._pod_node.pop(pname, None)
        for s in self.schedulers:
            s.remove_node(name)
        self.nodes.pop(name, None)
        self.cordoned.discard(name)
        old = self._hooked_alloc.pop(name, None)
        if old is not None:
            meshstate.unregister_dirty_hook(old)
        self.fit_index.unregister(name)
        self._occ_dirty.add(name)
        self._names_cache = None
        self._slices_cache = None

    def cordon(self, name: str, on: bool = True) -> None:
        """Mark a node unschedulable (maintenance): existing pods keep
        running, but no placement path (schedule, gangs, preemption,
        defrag migrations, reconcile re-placement) targets it until
        ``cordon(name, on=False)``."""
        if name not in self.nodes:
            raise KeyError(name)
        (self.cordoned.add if on else self.cordoned.discard)(name)
        self._event("cordon" if on else "uncordon", node=name)

    def drain(self, name: str, may_place=None):
        """Cordon + migrate every pod off the node. Returns
        (migrated, unplaced): migrated = freshly-placed copies on other
        nodes; unplaced = pods that fit nowhere else — they are EVICTED
        (resources released), the caller decides whether to queue them
        (the controller pends them for its reconcile loop) or restore.
        Surviving gang members migrate only within their mates' slice
        (the core gang invariant). ``may_place(pod) -> bool`` lets the
        caller veto individual migrations (the controller's gang
        reservation: drained pods must not cherry-pick chips held for an
        aged pending gang) — vetoed pods go straight to unplaced."""
        self.cordon(name)
        node = self.nodes[name]
        migrated, unplaced = [], []
        for pname in utils.sorted_string_keys(node.pods):
            template = _reset_for_reschedule(node.pods[pname])
            self.release(pname)
            if may_place is not None and not may_place(template):
                unplaced.append(template)
                continue
            try:
                migrated.append(
                    self.schedule(template, self.gang_slice_filter(template))
                )
            except SchedulingError:
                unplaced.append(template)
        self._event("drain", node=name, migrated=len(migrated),
                    unplaced=len(unplaced))
        return migrated, unplaced

    def refresh_node(self, name: str, probed: Optional[NodeInfo] = None) -> NodeInfo:
        """Re-probe a node's device manager and re-advertise, preserving the
        resources held by its placed pods — the periodic refresh the
        reference's CRI shim performs (UpdateNodeInfo on the 5-minute probe
        cadence, nvidia_gpu_manager.go:110-121). A chip that disappeared
        from the probe stops being advertised; chips held by pods are
        re-subtracted from the fresh allocatable.

        *probed*: a pre-probed advertisement to apply instead of probing
        here — lets callers that serialize cluster mutations under a lock
        keep the (slow, possibly remote) probe OUTSIDE it."""
        node = self.nodes.get(name)
        if node is None:
            raise KeyError(name)
        if node.device is None and probed is None:
            return node.info
        if probed is not None:
            fresh = probed
            fresh.name = name
        else:
            fresh = new_node_info(name)
            node.device.update_node_info(fresh)
        for pod in node.pods.values():
            group_scheduler.take_pod_resources(fresh, pod)
        node.info.capacity = fresh.capacity
        node.info.allocatable = fresh.allocatable
        node.info.kube_cap = fresh.kube_cap
        node.info.kube_alloc = fresh.kube_alloc
        for s in self.schedulers:
            s.add_node(name, node.info)
        # the advertisement dict was replaced (twice: assignment above,
        # then the schedulers' translation) — re-hook and re-index it
        self._index_register(name)
        return node.info

    # -- remote nodes (the agent wire) --------------------------------------

    def register_remote_node(
        self, url: str, name: Optional[str] = None, token: Optional[str] = None
    ) -> NodeInfo:
        """Register a node served by a live agent process (``kubetpu-agent
        --serve``): probe it over the wire and enter it into the scheduling
        loop exactly like an in-process manager. The node's advertised name
        is used unless *name* overrides it. Raises ``AgentUnreachable`` when
        no agent answers at *url*. Token-protected agents: pass *token*
        per agent (secrets may differ per node) or set ``KUBETPU_WIRE_TOKEN``
        for a fleet-wide default."""
        from kubetpu.wire.client import probe_remote_agent

        dev, info = probe_remote_agent(url, name=name, token=token)
        if info.name in self.nodes:
            # Silently replacing would drop the existing node's placed pods
            # from control-plane state; the caller must fail_node/remove_node
            # first (or name the agents distinctly).
            raise ValueError(
                f"node {info.name!r} is already registered; fail_node/remove_node "
                f"it first, or start the agent with a distinct --name"
            )
        self._event("register_remote", node=info.name, url=url)
        # probe=False: the advertisement above is fresh — don't re-GET.
        return self.register_node(info.name, device=dev, node_info=info, probe=False)

    def poll_remote_nodes(self) -> Dict[str, List[PodInfo]]:
        """Refresh every remote (agent-backed) node; a node whose agent has
        died is failed (``fail_node``) and its evicted pods returned, keyed
        by node name, for the caller to requeue — the cross-process leg of
        elastic recovery (SURVEY.md §5.3). Healthy nodes re-advertise, so
        chips that disappeared from a live agent's probe also stop being
        scheduled onto."""
        from kubetpu.wire import AgentUnreachable, RemoteDevice

        evicted: Dict[str, List[PodInfo]] = {}
        for name in utils.sorted_string_keys(self.nodes):
            node = self.nodes.get(name)
            if node is None or not isinstance(node.device, RemoteDevice):
                continue
            try:
                self.refresh_node(name)
            except AgentUnreachable:
                evicted[name] = self.fail_node(name)
            except RuntimeError as e:
                # The agent answered but its probe failed (HTTP 500): the
                # node is degraded, not dead — keep its last advertisement,
                # keep polling the rest of the fleet.
                utils.errorf("refresh of %s failed (degraded agent): %s", name, e)
        return evicted

    # -- per-pod scheduling (the hot path) ----------------------------------

    def schedule(
        self,
        pod: PodInfo,
        node_filter: Optional[Callable[[str], bool]] = None,
        candidates: Optional[Sequence[str]] = None,
    ) -> PodInfo:
        """Place one pod; returns the placed copy (with node_name and
        AllocateFrom filled). Raises SchedulingError when nothing fits.
        *candidates* restricts the sweep to an explicit node list (batch
        gang admission: the gang path already knows the slice's members /
        the pinned host, so per-member fleet filtering is pure waste)."""
        from kubetpu.obs import trace as obs_trace

        t0 = time.perf_counter()
        try:
            with obs_trace.span("cluster.schedule", pod=pod.name):
                return self._schedule_inner(pod, node_filter, candidates)
        finally:
            self.metrics.record("schedule_pod", time.perf_counter() - t0)

    def _schedule_inner(
        self,
        pod: PodInfo,
        node_filter: Optional[Callable[[str], bool]],
        candidates: Optional[Sequence[str]] = None,
    ) -> PodInfo:
        # Round-18 vChips: validate the fractional stamp up front — a
        # malformed milli value raises here (ValueError) instead of
        # failing as a mysterious "no node fits", and mixing the two
        # grammars in one pod is a config error, not a capacity miss.
        if meshstate.pod_milli(pod) > 0 and pod_device_need(TPU, pod) > 0:
            raise SchedulingError(
                f"pod {pod.name!r}: cannot mix whole-chip and vChip "
                f"({meshstate.FracKey}) requests"
            )
        # One scratch copy serves the whole predicate sweep: fit/score never
        # read the translation artifacts a previous node left in it (the fit
        # decision is scalar pre-filter + shape cache + mesh geometry), and
        # the winner is re-translated from a FRESH copy below — so per-node
        # copies would only feed the garbage collector (512-node p50).
        scratch = pod.copy()
        # Provably-best achievable score across schedulers (None = unknown):
        # the sweep visits nodes in sorted-name order and the final pick is
        # (-score, name)-sorted, so the FIRST node reaching the bound IS the
        # winner — stop scanning there (O(first perfect node), not O(nodes)).
        bound: Optional[float] = 0.0
        for s in self.schedulers:
            b = s.perfect_score(scratch)
            if b is None:
                bound = None
                break
            bound += b
        names, caps, pruned = self._sweep_names(scratch, node_filter, candidates)
        # Cross-check oracle: compute what the UNPRUNED sweep would try
        # first, before the index path mutates anything, and fail loudly
        # on any divergence (the equivalence guarantee, enforced).
        reference: object = _NO_REFERENCE
        if self.index_cross_check and pruned:
            reference = self._reference_pick(
                scratch, node_filter, candidates, bound
            )
            self.index_stats["cross_checks"] += 1
        # Fitting candidates ride a heap keyed (-score, name): each is
        # pushed once at sweep time and popped once at try time, so the
        # early-exit/resume path stays O(log n) per step instead of
        # re-sorting the whole candidate list every resume iteration.
        fit_heap: List[tuple] = []
        any_fit = False
        first_try: Optional[tuple] = None
        idx = 0

        def can_settle(top_score: float, at: int) -> bool:
            """May the sweep stop scanning and commit to the heap top?
            Yes when the sweep is exhausted, or when no unvisited node can
            beat *top_score*: the per-name cap when the index ordered the
            visit best-first (caps are EXACT and descending, and equal-cap
            names ascend, so a tied unvisited node never wins the (-score,
            name) tie-break), else the global perfect-score bound."""
            if at >= len(names):
                return True
            limit = caps[at] if caps is not None else bound
            return limit is not None and top_score >= limit - 1e-9

        while True:
            # sweep (resumable): collect fitting nodes; stop early once the
            # best node seen provably beats everything unvisited — at a
            # bound-reaching node (name order), or at the next cap (score
            # order): either way the heap top IS the sweep's winner
            while idx < len(names):
                name = names[idx]
                idx += 1
                node = self.nodes[name]
                fits = True
                score = 0.0
                for s in self.schedulers:
                    ok, _reasons, sc = s.pod_fits_device(node.info, scratch, False)
                    if not ok:
                        fits = False
                        break
                    score += sc
                if fits:
                    any_fit = True
                    heapq.heappush(fit_heap, (-score, name))
                    if can_settle(-fit_heap[0][0], idx):
                        break

            # Best score first; if the group-scheduler fill disagrees with
            # the fit (e.g. stale scalar vs. actual free cards), demote the
            # node and try the next candidate — and when the early exit
            # truncated the sweep, RESUME it rather than settling: an
            # unscanned node may still beat the heap top, and a best-score
            # placement must never silently degrade to a lesser one.
            while fit_heap:
                neg_score, name = fit_heap[0]
                if not can_settle(-neg_score, idx):
                    break  # resume the sweep before trying beatable nodes
                heapq.heappop(fit_heap)
                if first_try is None:
                    first_try = (name, -neg_score)
                    if reference is not _NO_REFERENCE and reference != first_try:
                        raise RuntimeError(
                            f"fit-index divergence for pod {pod.name!r}: "
                            f"index path tries {first_try}, full sweep "
                            f"picks {reference}"
                        )
                node = self.nodes[name]
                pod_copy = pod.copy()
                for s in self.schedulers:
                    s.pod_allocate(node.info, pod_copy)
                if not group_scheduler.fill_allocate_from(node.info, pod_copy):
                    utils.logf(3, "pod %s: fill failed on %s, trying next node", pod.name, name)
                    continue
                group_scheduler.take_pod_resources(node.info, pod_copy)
                for s in self.schedulers:
                    s.take_pod_resources(node.info, pod_copy)
                pod_copy.node_name = name
                node.pods[pod_copy.name] = pod_copy
                self._pod_node[pod_copy.name] = name
                utils.logf(3, "scheduled pod %s on %s (score %.3f)", pod.name, name, -neg_score)
                self._event("schedule", pod=pod_copy.name, node=name, score=-neg_score)
                return pod_copy
            if idx >= len(names):
                if not any_fit:
                    if reference is not _NO_REFERENCE and reference is not None:
                        raise RuntimeError(
                            f"fit-index divergence for pod {pod.name!r}: "
                            f"index path finds no fit, full sweep picks "
                            f"{reference}"
                        )
                    raise SchedulingError(f"pod {pod.name!r}: no node fits")
                raise SchedulingError(
                    f"pod {pod.name!r}: fill failed on every fitting node"
                )

    def _sweep_names(
        self,
        scratch: PodInfo,
        node_filter: Optional[Callable[[str], bool]],
        candidates: Optional[Sequence[str]],
    ) -> Tuple[List[str], Optional[List[float]], bool]:
        """The node names _schedule_inner sweeps, an optional aligned list
        of EXACT per-name score caps (frac fast path — visit order is then
        best-score-first instead of name order), and whether the fit index
        pruned. Three narrowing layers compose: the explicit candidate
        list (batch gang admission), the index prune (nodes *provably
        failing* the schedulers' cheapest pre-filters dropped), and the
        cordon/node_filter gate the full sweep always applied. Soundness:
        the surviving names flow through the UNCHANGED sweep machinery, so
        pruning can only skip work, never change the decision — see the
        fitindex module docstring; for the cap-ordered variant see
        _schedule_inner's settle rule."""
        pool: Optional[Set[str]] = None
        ordered: Optional[List[Tuple[str, float]]] = None
        pruned = False
        # An explicit candidate list (batch gang admission, pinned
        # re-placements) is already narrower than any prune could make
        # it — consulting the index there costs an ensure_fresh plus a
        # fleet-wide bucket query to discard at most a handful of names
        # (measured 1.7x on the 256-chip gang bench). The sweep over the
        # explicit list is the cheap path; skip the index entirely.
        if self.use_fit_index and candidates is None:
            ans = self._index_eligible(scratch)
            if ans is None:
                self.index_stats["fallback_sweeps"] += 1
            else:
                self.index_stats["pruned_sweeps"] += 1
                pruned = True
                pool, ordered = ans
        if ordered is not None:
            # frac fast path: keep the index's (desc score, asc name)
            # order and its caps; apply the same gates positionally.
            names: List[str] = []
            caps: List[float] = []
            for n, cap in ordered:
                if n in self.cordoned:
                    continue
                if node_filter is not None and not node_filter(n):
                    continue
                names.append(n)
                caps.append(cap)
            return names, caps, pruned
        if candidates is not None:
            explicit = {n for n in candidates if n in self.nodes}
            pool = explicit if pool is None else (pool & explicit)
        if pool is None:
            base: Sequence[str] = self._sorted_names()
        else:
            base = sorted(pool)
        return [
            n
            for n in base
            if n not in self.cordoned
            and (node_filter is None or node_filter(n))
        ], None, pruned

    def _index_eligible(
        self, scratch: PodInfo
    ) -> Optional[Tuple[Optional[Set[str]], Optional[List[Tuple[str, float]]]]]:
        """Index answer for *scratch*: ``(eligible_set, None)`` for the
        set prune, ``(None, ordered_caps)`` for the frac fast path, or
        None when the index cannot answer soundly: an unconstrained pod
        (nothing to prune on), or index/registry drift — the STALENESS
        FALLBACK: on any detectable desync the full sweep runs and stays
        authoritative (the index never guesses)."""
        try:
            frac = meshstate.pod_milli(scratch)
        except ValueError:
            return None
        # pod_device_need is the pre-translation request count — exactly
        # the `want` the schedulers' scalar pre-filters compare against.
        want_tpu = 0 if frac > 0 else pod_device_need(TPU, scratch)
        want_gpu = pod_device_need(GPU, scratch)
        if not (frac or want_tpu or want_gpu):
            return None
        idx = self.fit_index
        idx.ensure_fresh(self._index_alloc)
        if len(idx.entries) != len(self.nodes):
            return None  # registry drift: sweep, don't guess
        if frac > 0 and want_gpu == 0 and self._caps_ok:
            # Pure-vChip pod under the stock schedulers: the index knows
            # each candidate's exact total score (frac_ordered docstring),
            # so the sweep can go best-first with O(1) evaluations.
            return None, idx.frac_ordered(frac)
        return idx.eligible(want_tpu, want_gpu, frac), None

    def _reference_pick(
        self,
        scratch: PodInfo,
        node_filter: Optional[Callable[[str], bool]],
        candidates: Optional[Sequence[str]],
        bound: Optional[float],
    ):
        """Cross-check ground truth: the (node, score) the full O(fleet)
        predicate sweep would try FIRST — fit-only, no fill, no commit;
        None when nothing fits. Mirrors _schedule_inner's selection rule
        exactly: first bound-reacher in sorted-name order wins, else the
        (-score, name) minimum over all fitting nodes."""
        if candidates is not None:
            base: Sequence[str] = sorted(
                {n for n in candidates if n in self.nodes}
            )
        else:
            base = self._sorted_names()
        best: Optional[tuple] = None
        for name in base:
            if name in self.cordoned or (
                node_filter is not None and not node_filter(name)
            ):
                continue
            node = self.nodes[name]
            fits = True
            score = 0.0
            for s in self.schedulers:
                ok, _reasons, sc = s.pod_fits_device(node.info, scratch, False)
                if not ok:
                    fits = False
                    break
                score += sc
            if not fits:
                continue
            if bound is not None and score >= bound - 1e-9:
                return (name, score)
            if best is None or (-score, name) < best:
                best = (-score, name)
        return None if best is None else (best[1], -best[0])

    def _find_pod_node(self, pod_name: str) -> Optional[ClusterNode]:
        """O(1) pod -> node resolution via the pod map, with a defensive
        linear-sweep fallback: a desynced map is an invariant violation
        (check_invariants audits it), but lookups must stay correct even
        then. None when the pod is placed nowhere."""
        mapped = self._pod_node.get(pod_name)
        if mapped is not None:
            node = self.nodes.get(mapped)
            if node is not None and pod_name in node.pods:
                return node
        for node in self.nodes.values():
            if pod_name in node.pods:
                self._pod_node[pod_name] = node.info.name  # repair the map
                return node
        return None

    def pod_node(self, pod_name: str) -> Optional[str]:
        """Which node hosts this placed pod (None when unplaced) — the
        public O(1) face of the pod map, for callers (controller handlers,
        gauges) that used to scan ``nodes.items()`` per lookup."""
        node = self._find_pod_node(pod_name)
        return None if node is None else node.info.name

    def release(self, pod_name: str) -> None:
        """Return a pod's resources (pod deletion). O(1) via the pod map
        (used to scan every node)."""
        node = self._find_pod_node(pod_name)
        if node is None:
            self._pod_node.pop(pod_name, None)
            raise KeyError(pod_name)
        placed = node.pods.pop(pod_name)
        self._pod_node.pop(pod_name, None)
        group_scheduler.return_pod_resources(node.info, placed)
        for s in self.schedulers:
            s.return_pod_resources(node.info, placed)
        self._event("release", pod=pod_name, node=node.info.name)

    # -- container start (CRI step) -----------------------------------------

    def allocate(self, pod_name: str) -> Dict[str, AllocateResult]:
        """Run the device manager's Allocate for each container of a placed
        pod — the container-start injection step (SURVEY.md §3.4). O(1)
        via the pod map (used to scan every node)."""
        node = self._find_pod_node(pod_name)
        if node is None:
            raise KeyError(pod_name)
        placed = node.pods[pod_name]
        if node.device is None:
            raise RuntimeError(f"node {node.info.name} has no device manager")
        out: Dict[str, AllocateResult] = {}
        for cname, cont in sorted(placed.init_containers.items()):
            out[cname] = node.device.allocate(placed, cont)
        for cname, cont in sorted(placed.running_containers.items()):
            out[cname] = node.device.allocate(placed, cont)
        return out

    # -- gang scheduling ----------------------------------------------------

    def new_gang_id(self) -> int:
        """Fresh gang-identity stamp (a ``GangKey`` value) for pods that
        enter a pending queue as a gang BEFORE any placement (the
        controller's queued submissions). ``schedule_gang`` re-stamps on
        placement, so uniqueness is all that matters here."""
        self._gang_seq += 1
        return self._gang_seq

    def schedule_gang(self, pods: Sequence[PodInfo]) -> List[PodInfo]:
        """All-or-nothing placement of a gang (one pod per host of a
        multi-host job): either every pod lands or none does.

        The reference punts gang semantics to the external core's group
        scheduler (``UsingGroupScheduler``, gpu_scheduler.go:69-71); kubetpu
        implements them: try to keep the gang on a single slice (nodes that
        advertise the same tpu-slice topology), hosts in index order so the
        chosen host blocks tile a contiguous torus region; roll back fully
        on any failure.

        Multislice (opt-in): when every pod carries the
        ``kubetpu/multislice`` knob with value k >= 2 and no single slice
        fits, the gang may span up to k physical slices — data parallelism
        rides DCN between the slices, ICI parallelism within each (the
        third locality level the reference's two-level NVLink/PCIe tree,
        nvidia_gpu_manager.go:74-88, never needed). Each sub-gang is placed
        with the same per-slice geometric contiguity as a single-slice
        gang, and members are stamped with ``kubetpu/gang-slices`` /
        ``kubetpu/gang-slice-id`` so Allocate can emit the libtpu
        multislice env and re-placements rejoin the right sub-gang.
        """
        from kubetpu.obs import trace as obs_trace

        t0 = time.perf_counter()
        try:
            with obs_trace.span("cluster.schedule_gang", pods=len(pods)):
                return self._schedule_gang_inner(pods)
        finally:
            self.metrics.record("schedule_gang", time.perf_counter() - t0)

    def _schedule_gang_inner(self, pods: Sequence[PodInfo]) -> List[PodInfo]:
        # Stamp gang identity on copies (inputs are templates): members
        # carry it through placement, eviction, and reset, so a later
        # individual re-place can find its surviving gang mates. Stale
        # slice-membership stamps from a PREVIOUS placement of the same
        # templates are dropped — only a fresh multislice placement may
        # set them, or a single-slice re-place would leave members
        # claiming sub-gangs that no longer exist.
        self._gang_seq += 1
        pods = [p.copy() for p in pods]
        for p in pods:
            p.requests[GangKey] = self._gang_seq
            p.requests.pop(meshstate.GangSlicesKey, None)
            p.requests.pop(meshstate.GangSliceIdKey, None)
        slices = self._tpu_slices()
        # pod_wants_device covers device-native AND kube-native requests
        # over both container kinds, so a kube-only gang is still pinned
        # to a single slice below. Fractional (vChip) members count too:
        # an all-fractional gang is still an ICI gang and must land
        # within one slice.
        tpu_gang = bool(pods) and all(
            pod_wants_device(TPU, pod) or meshstate.pod_milli(pod) > 0
            for pod in pods
        )
        # provable-capacity pre-filter, in MILLI-chips (Round-18): a
        # slice whose free fractional capacity cannot cover the gang's
        # total need would fail only after placing (and rolling back)
        # pods one by one — at 60-pod gangs that wasted pass per slice
        # dominates placement latency. pod_device_need (not _count):
        # these are UN-translated templates, so the kube/device
        # max-merge must apply inline.
        total_need = (
            sum(self._pod_need_millis(p) for p in pods) if tpu_gang else 0
        )
        for slice_nodes in slices.values():
            # cordoned hosts never host gang members; NOTE a slice with
            # fewer (uncordoned) hosts than pods can still fit the gang
            # by co-locating sub-host pods — no count-based skip here
            slice_nodes = [n for n in slice_nodes
                           if n not in self.cordoned]
            if not slice_nodes:
                continue
            if tpu_gang and self._slice_free_millis(slice_nodes) < total_need:
                continue
            try:
                return self._try_gang_slice(pods, slice_nodes)
            except SchedulingError:
                continue
        if tpu_gang and slices:
            # Opt-in escape hatch: span up to k slices when no single
            # slice fits (the knob must be on EVERY member — a gang
            # half-willing to cross DCN is a config error, treated as
            # unwilling).
            max_slices = min(
                (int(p.requests.get(meshstate.MultisliceKey, 0)) for p in pods),
                default=0,
            )
            if max_slices >= 2:
                return self._try_gang_multislice(pods, slices, max_slices)
            # A TPU gang must live inside ONE physical slice: chips in
            # different slices are connected over DCN, not ICI, and a
            # silent straddle would wreck the job's collectives.
            raise SchedulingError(
                f"gang of {len(pods)} pods does not fit within any single "
                f"TPU slice ({', '.join(slices)})"
            )
        # non-TPU gangs (or clusters without slice geometry): anywhere
        return self._try_gang(pods, None)

    @staticmethod
    def _pod_need_millis(pod: PodInfo) -> int:
        """A gang template's TPU need in milli-chips: its vChip share
        when fractional, its (max-merged) whole-chip count otherwise —
        the common currency of the fractional capacity pre-filter."""
        frac = meshstate.pod_milli(pod)
        if frac > 0:
            return frac
        return max(1, pod_device_need(TPU, pod)) * meshstate.MILLI_PER_CHIP

    def _slice_free_millis(self, nodes: Sequence[str]) -> int:
        """Free capacity across a slice's (already cordon-filtered) nodes
        in MILLI-chips — the ONE free-capacity tally both the
        single-slice pre-filter and the multislice candidate ordering
        use. Whole-free chips count MILLI_PER_CHIP each; partially
        occupied chips contribute their fractional remainder (Round-18:
        ``_slice_free_chips`` generalized to a fractional capacity sum).
        Served from the fit index when fresh entries cover every node
        (same free_milli computation, cached per node instead of
        re-parsed per call)."""
        if self.use_fit_index:
            idx = self.fit_index
            idx.ensure_fresh(self._index_alloc)
            entries = idx.entries
            if all(n in entries for n in nodes):
                return sum(entries[n].free_milli for n in nodes)
        return sum(
            st.free_milli()
            for n in nodes
            if (st := meshstate.parse_mesh_state(
                self.nodes[n].info.allocatable)) is not None
        )

    def _try_gang_slice(
        self, pods: Sequence[PodInfo], slice_nodes: List[str]
    ) -> List[PodInfo]:
        """Place a (sub-)gang entirely within one slice's nodes. Best case:
        assign pods to a *geometrically contiguous set of host blocks* (a
        2-host gang on a v5e-64 should get two vertically adjacent hosts
        forming a 4x4 square, not a 2x8 strip); fall back to any placement
        confined to the slice."""
        ordered_hosts = self._contiguous_hosts(slice_nodes, len(pods))
        if ordered_hosts is not None:
            try:
                return self._try_gang_pinned(pods, ordered_hosts)
            except SchedulingError:
                pass
        # Batch admission: hand the slice's member list straight to the
        # per-pod sweep as explicit candidates — the old per-member
        # node_filter still forced each pod to walk the WHOLE fleet's
        # name list just to discard everything outside the slice.
        return self._try_gang(pods, None, candidates=slice_nodes)

    def _try_gang_multislice(
        self,
        pods: List[PodInfo],
        slices: Dict[str, List[str]],
        max_slices: int,
    ) -> List[PodInfo]:
        """Partition the gang over k distinct physical slices, trying the
        fewest slices first (k = 2 upward — every extra slice is another
        DCN leg). Sub-gangs are EQUAL-SIZED contiguous chunks of the pod
        list: the jobs-side ``dcn`` mesh axis (``make_multislice_mesh``)
        needs the same device count in every slice, so a lopsided split
        would schedule a gang that cannot build its mesh — k values that
        do not divide the gang are skipped. (Equality is in PODS; gangs
        with heterogeneous per-pod chip counts should keep worker shapes
        uniform, as multi-host jobs do anyway.) Candidate slices are
        tried fullest-first; each sub-gang gets the same per-slice
        geometric contiguity treatment as a single-slice gang. All-or-
        nothing: any shortfall rolls back every placed member and the
        next k is tried.

        On success every member is stamped with its slice membership
        (``gang-slices`` = k, ``gang-slice-id`` = this pod's sub-gang
        index, in pod order) — the device manager turns those into
        MEGASCALE_NUM_SLICES / MEGASCALE_SLICE_ID at container start, and
        ``gang_slice_filter`` uses them to pin re-placements to the pod's
        OWN sub-gang's slice."""
        free_millis: Dict[str, int] = {
            sname: self._slice_free_millis(
                [n for n in nodes if n not in self.cordoned]
            )
            for sname, nodes in slices.items()
        }
        order = sorted(slices, key=lambda s: (-free_millis[s], s))
        needs = [self._pod_need_millis(p) for p in pods]

        for k in range(2, min(max_slices, len(order), len(pods)) + 1):
            if len(pods) % k:
                continue
            sub_n = len(pods) // k
            groups: List[List[PodInfo]] = []
            for sname in order:
                if len(groups) == k:
                    break
                nodes = [n for n in slices[sname] if n not in self.cordoned]
                if not nodes:
                    continue
                lo = len(groups) * sub_n
                if sum(needs[lo : lo + sub_n]) > free_millis[sname]:
                    continue  # provably too full for a sub-gang
                try:
                    groups.append(
                        self._try_gang_slice(pods[lo : lo + sub_n], nodes)
                    )
                except SchedulingError:
                    continue
            if len(groups) < k:
                for sub in groups:  # all-or-nothing at this k
                    for p in sub:
                        self.release(p.name)
                continue
            placed_all: List[PodInfo] = []
            for sid, sub in enumerate(groups):
                for p in sub:
                    # placed copies live in node.pods — stamps persist
                    p.requests[meshstate.GangSlicesKey] = k
                    p.requests[meshstate.GangSliceIdKey] = sid
                placed_all.extend(sub)
            self._event(
                "schedule_multislice", gang=pods[0].requests.get(GangKey),
                slices=k, pods=len(placed_all),
            )
            return placed_all
        raise SchedulingError(
            f"gang of {len(pods)} pods does not fit within {max_slices} TPU "
            f"slices in equal sub-gangs ({', '.join(slices)}) — the dcn "
            f"mesh axis needs the same device count per slice"
        )

    def _contiguous_hosts(self, slice_nodes: List[str], k: int) -> Optional[List[str]]:
        """Pick k host-nodes of one slice whose blocks tile a contiguous
        region of the torus, via rectangle search on the *host grid*."""
        if k > len(slice_nodes):
            return None
        states = {}
        for name in slice_nodes:
            st = meshstate.parse_mesh_state(self.nodes[name].info.allocatable)
            if st is None:
                return None
            states[name] = st
        topo = next(iter(states.values())).topo
        hosts_per_dim = tuple(m // h for m, h in zip(topo.mesh_shape, topo.host_shape))
        host_grid = TpuTopology(
            name=topo.name + "-hostgrid",
            generation=topo.generation,
            mesh_shape=hosts_per_dim,
            wrap=topo.wrap,
            host_shape=tuple(1 for _ in hosts_per_dim),
        )
        # host index <-> host-grid coordinate (row-major, mesh.py host_of)
        free_host_coords = {}
        for name, st in states.items():
            if st.free:  # host has free chips at all
                free_host_coords[host_grid.index_coord(st.host_index)] = name

        # Rank host-grid rectangle shapes by the CHIP-level links of the
        # resulting region, not host-grid compactness: host blocks are
        # anisotropic (2x4), so 2 hosts stacked along x give a 4x4 chip
        # square while 2 along y give a 2x8 strip. (memoized pure geometry)
        shapes = [
            s
            for s in factorizations(k, len(hosts_per_dim))
            if all(d <= m for d, m in zip(s, hosts_per_dim))
        ]
        shapes.sort(key=lambda s: (-host_block_links(topo, s), s))
        free_set = set(free_host_coords)
        for shape in shapes:
            for block in enumerate_blocks(host_grid, shape):
                if all(c in free_set for c in block):
                    return [free_host_coords[c] for c in sorted(block)]
        # no exact host rectangle: fall back to greedy host-grid growth
        placed = find_contiguous_block(free_set, k, host_grid)
        if placed is None:
            return None
        coords, _score = placed
        return [free_host_coords[c] for c in coords]

    def _try_gang_pinned(
        self, pods: Sequence[PodInfo], ordered_hosts: List[str]
    ) -> List[PodInfo]:
        """Schedule pod i on host i exactly, rolling back on any failure.
        The pin is an explicit one-element candidate list, so each member's
        placement is O(its own host), not O(fleet filter sweep) — the batch
        gang admission fast path: one index pass chose the hosts, each
        member only re-validates its own."""
        placed: List[PodInfo] = []
        try:
            for pod, host in zip(pods, ordered_hosts):
                placed.append(self.schedule(pod, candidates=[host]))
        except SchedulingError:
            for p in placed:
                self.release(p.name)
            raise
        return placed

    def _restore_pods(self, pods: Sequence[PodInfo], node_name: str) -> List[PodInfo]:
        """Best-effort re-placement of released pods (rollback paths):
        pinned to *node_name* first (their resources are typically still
        free there), anywhere as fallback. Returns the pods that could not
        be restored — callers must surface those, never drop them."""
        lost: List[PodInfo] = []
        for p in pods:
            try:
                self.schedule(p.copy(), candidates=[node_name])
                continue
            except SchedulingError:
                pass
            try:
                self.schedule(p.copy())
            except SchedulingError:
                lost.append(p)
        return lost

    def _try_gang(
        self,
        pods: Sequence[PodInfo],
        node_filter: Optional[Callable[[str], bool]],
        candidates: Optional[Sequence[str]] = None,
    ) -> List[PodInfo]:
        placed: List[PodInfo] = []
        try:
            for pod in pods:
                placed.append(self.schedule(pod, node_filter, candidates))
        except SchedulingError:
            for p in placed:  # rollback — all-or-nothing
                self.release(p.name)
            raise
        return placed

    def gang_slice_filter(self, pod: PodInfo) -> Optional[Callable[[str], bool]]:
        """Node filter honoring a re-placed pod's gang slice affinity: when
        surviving members of its gang are placed on a TPU slice, only that
        slice's nodes are eligible — the single-slice gang invariant
        (schedule_gang's DCN guard) applies to RE-placements too. For a
        multislice gang member the affinity is to its OWN sub-gang's slice
        (mates sharing its ``gang-slice-id``) — rejoining a DIFFERENT
        sub-gang's slice would silently change the job's DCN topology.
        None when the pod carries no gang id or has no placed (sub-)gang
        mates."""
        gid = pod.requests.get(GangKey)
        if not gid:
            return None
        sid = pod.requests.get(meshstate.GangSliceIdKey)
        has_sid = meshstate.GangSliceIdKey in pod.requests
        other_slices: set = set()  # nodes of OTHER sub-gangs' slices
        for node in self.nodes.values():
            for placed in node.pods.values():
                if placed.name == pod.name or placed.requests.get(GangKey) != gid:
                    continue
                state = meshstate.parse_mesh_state(node.info.allocatable)
                if state is None:
                    return None  # non-mesh gang: no slice constraint
                members = set(self._tpu_slices().get(state.slice_name, []))
                if has_sid and placed.requests.get(meshstate.GangSliceIdKey) != sid:
                    # a mate of a DIFFERENT sub-gang pins its own slice:
                    # not this pod's home, but ground this pod must avoid
                    other_slices |= members
                    continue
                return lambda n, m=members: n in m
        if other_slices:
            # This pod's whole sub-gang is evicted but other sub-gangs are
            # placed: re-place anywhere EXCEPT their slices — landing there
            # would put two MEGASCALE "slices" on one physical slice and
            # silently corrupt the job's DCN topology. The first member to
            # land re-pins the rest via the same-sid branch above.
            return lambda n, m=other_slices: n not in m
        return None

    def _tpu_slices(self) -> Dict[str, List[str]]:
        """Slice name -> node names sorted by host index. Cached between
        node add/remove/refresh: slice membership is advertisement
        GEOMETRY (the tpu-slice key), which accounting never touches, so
        re-deriving it per gang/drain/filter call was pure fleet-sized
        waste. Callers must not mutate the returned structure."""
        if self._slices_cache is None:
            slices: Dict[str, List[tuple]] = {}
            for name, node in self.nodes.items():
                state = meshstate.parse_mesh_state(node.info.allocatable)
                if state is not None:
                    slices.setdefault(state.slice_name, []).append(
                        (state.host_index, name))
            self._slices_cache = {
                s: [n for _, n in sorted(members)]
                for s, members in sorted(slices.items())
            }
        return self._slices_cache

    # -- priorities & preemption ---------------------------------------------

    def schedule_preempting(
        self, pod: PodInfo
    ) -> Tuple[PodInfo, List[PodInfo]]:
        """Place a pod, evicting strictly-lower-priority pods if (and only
        if) that makes it fit. Returns (placed pod, evicted pods — reset to
        schedulable form for the caller to requeue).

        Priority rides the pod's Requests as the pseudo-resource
        ``kubetpu/priority`` (default 0) — the same resource-list-as-config
        channel as the reference's topology knob (SURVEY.md §5.6).
        Feasibility is checked geometrically BEFORE any eviction: victims
        are only killed when the freed chips provably yield a contiguous
        block, cheapest (lowest-priority) victims first.
        """
        try:
            return self.schedule(pod), []
        except SchedulingError:
            pass

        prio = pod_priority(pod)
        probe = pod.copy()
        # Same kube/device max-merge as set_device_reqs, over BOTH container
        # kinds and BOTH device classes — a pod carrying its count only in
        # an init container's kube_requests is still preemption-eligible
        # (mirrors the schedule_gang TPU-gang detection above).
        for cont in itertools.chain(
            probe.running_containers.values(), probe.init_containers.values()
        ):
            for dc in (TPU, GPU):
                cont.requests[dc.resource_name] = max(
                    cont.requests.get(dc.resource_name, 0),
                    cont.kube_requests.get(dc.resource_name, 0),
                )
        n_tpu = pod_device_count(TPU, probe)
        n_gpu = pod_device_count(GPU, probe)
        frac = meshstate.pod_milli(probe)
        if n_tpu == 0 and n_gpu == 0 and frac == 0:
            raise SchedulingError(f"pod {pod.name!r}: no node fits (nothing to preempt for)")

        for name in utils.sorted_string_keys(self.nodes):
            if name in self.cordoned:
                continue  # maintenance nodes take no new pods, even by force
            node = self.nodes[name]
            state = meshstate.parse_mesh_state(node.info.allocatable)
            if (n_tpu > 0 or frac > 0) and state is None:
                continue  # the TPU leg needs mesh geometry on this node
            victims = sorted(
                (p for p in node.pods.values() if pod_priority(p) < prio),
                key=pod_priority,
            )
            # Feasibility per device class: TPU is geometric (evictions must
            # provably open a contiguous block); GPU (tree) is scalar — the
            # structural fill spills across NVLink groups, so free count is
            # exact (group_scheduler._pick_pool_tree fails only on count).
            # Round-18 fractional: evictions are tracked per chip in
            # milli-chips — a chip rejoins the whole-free set only when
            # its LAST fractional occupant is gone (exact restoration),
            # and a vChip preemptor fits once any chip's freed milli
            # covers its share.
            avail = set(state.free) if state is not None else set()
            frac_free: Dict = dict(state.frac_free) if state is not None else {}
            free_gpu = node.info.allocatable.get(GPU.resource_name, 0)
            chosen: List[PodInfo] = []

            def _fits() -> bool:
                if n_tpu > 0 and find_contiguous_block(avail, n_tpu, state.topo) is None:
                    return False
                if frac > 0 and not any(
                    f >= frac for f in frac_free.values()
                ):
                    return False
                return not (n_gpu > 0 and free_gpu < n_gpu)

            fits = _fits()
            for victim in victims:
                if fits:
                    break
                # Evict only victims that actually free the needed device
                # class — a CPU-only (or wrong-class) neighbor must not be
                # killed for nothing.
                contributes = False
                if n_tpu > 0 or frac > 0:
                    _topo, vcoords = self.pod_chip_coords(victim)
                    fresh_coords = set(vcoords) - avail
                    if fresh_coords:
                        avail |= fresh_coords
                        contributes = True
                        # a freed WHOLE chip is fractional capacity too
                        # (only vChip-capable chips — those advertising
                        # a /milli key — can host a share)
                        for c in fresh_coords:
                            local = state.coord_chip.get(c)
                            if local in state.milli_key:
                                frac_free[c] = meshstate.MILLI_PER_CHIP
                    for key, amt in group_scheduler.held_milli(
                            victim).items():
                        mm = meshstate.CHIP_MILLI_RE.match(key)
                        local = int(mm.group(1)) if mm else -1
                        if local not in state.chip_coord:
                            continue
                        c = state.chip_coord[local]
                        frac_free[c] = frac_free.get(c, 0) + amt
                        contributes = True
                        if frac_free[c] >= meshstate.MILLI_PER_CHIP:
                            # every fractional occupant evicted: the
                            # chip is whole again
                            avail.add(c)
                if n_gpu > 0:
                    cards = group_scheduler.held_cards(victim, GPU.base)
                    if cards:
                        free_gpu += len(cards)
                        contributes = True
                if not contributes:
                    continue
                chosen.append(victim)
                fits = _fits()
            if not fits:
                continue
            evicted: List[PodInfo] = []
            for victim in chosen:
                self.release(victim.name)
                evicted.append(_reset_for_reschedule(victim))
            # The geometric pre-check is TPU-only: the pinned schedule can
            # still fail on another dimension (e.g. the pod also wants GPUs
            # this node lacks). Never drop the already-evicted victims —
            # restore them (their resources are still free) and move on to
            # the next candidate node.
            try:
                placed = self.schedule(pod, candidates=[name])
            except SchedulingError:
                lost = self._restore_pods(evicted, name)
                if lost:  # cannot happen while resources are untouched, but
                    # never swallow a pod silently
                    raise SchedulingError(
                        f"pod {pod.name!r}: preemption rollback failed to "
                        f"restore {[p.name for p in lost]} on {name}"
                    )
                continue
            utils.logf(
                0, "pod %s (priority %d) preempted %s on %s",
                pod.name, prio, [v.name for v in evicted], name,
            )
            self._event("preempt", pod=pod.name, node=name,
                        victims=[v.name for v in evicted])
            return placed, evicted
        raise SchedulingError(
            f"pod {pod.name!r}: no node fits even with preemption at priority {prio}"
        )

    # -- defragmentation ------------------------------------------------------

    def defrag_plan(
        self, chips: int, max_migrations: int = 3, device: str = "tpu"
    ) -> Optional[List["Migration"]]:
        """When *fragmentation* (not capacity) blocks a perfect
        (contiguity-1.0) rectangular ``chips``-block, propose the smallest
        pod-migration set that opens one: vacating those pods must provably
        yield an exact rectangle on the source node AND each vacated pod
        must provably re-place — on another node or back onto the source
        node outside the opened block. Returns [] if a perfect block already
        fits somewhere, None if no plan within ``max_migrations`` moves
        exists (raise the cap for deeper searches; the search is
        combinatorial in it). Proposals only — ``execute_defrag`` applies.

        ``device="gpu"`` plans for tree nodes instead: "perfect" there means
        *chips* free cards within ONE level-1 (socket) group — the NVLink
        locality the structural fill silently gives up when it spills
        (reference grouping semantics, nvidia_gpu_manager.go:74-88).

        Victims are single-class pods only, and ``execute_defrag`` re-places
        each victim through the full scheduler (with rollback), so a plan
        invalidated by concurrent scheduling fails safely rather than
        dropping pods.
        """
        if device == GPU.base:
            return self._defrag_plan_tree(chips, max_migrations)
        states = {}
        # cordoned nodes are invisible to the plan: neither their free
        # blocks (an "already fits" there is unplaceable — schedule skips
        # them) nor as migration destinations (execute_defrag's pinned
        # schedule would refuse), matching cordon()'s contract
        for name in utils.sorted_string_keys(self.nodes):
            if name in self.cordoned:
                continue
            st = meshstate.parse_mesh_state(self.nodes[name].info.allocatable)
            if st is not None:
                states[name] = st
        for name, st in states.items():
            if find_perfect_block(set(st.free), chips, st.topo) is not None:
                return []  # no defrag needed

        for name, st in states.items():
            if len(st.free) < chips:
                continue  # capacity problem, not fragmentation
            node = self.nodes[name]
            # hoist: victim -> its chip coords, once per node
            victim_coords = {}
            for p in sorted(node.pods.values(), key=lambda p: p.name):
                # plan only TPU-geometry pods (placed pods carry zero-valued
                # scalar keys from every scheduler's max-merge — only a real
                # GPU request disqualifies)
                if any(
                    c.requests.get(ResourceGPU, 0) > 0
                    for c in p.running_containers.values()
                ):
                    continue
                _t, vcoords = self.pod_chip_coords(p)
                if vcoords:
                    victim_coords[p.name] = (p, vcoords)
            resident = list(victim_coords.values())
            for r in range(1, min(max_migrations, len(resident)) + 1):
                for combo in itertools.combinations(resident, r):
                    avail = set(st.free)
                    for _victim, vcoords in combo:
                        avail |= set(vcoords)
                    block = find_perfect_block(avail, chips, st.topo)
                    if block is None:
                        continue
                    # can every vacated pod land contiguously elsewhere —
                    # or back on this node outside the opened block?
                    dest_free = {
                        o: set(s2.free) for o, s2 in states.items() if o != name
                    }
                    dest_free[name] = avail - set(block)
                    plan: List[Migration] = []
                    feasible = True
                    for victim, vcoords in combo:
                        need = len(vcoords)
                        placed = False
                        for o in utils.sorted_string_keys(dest_free):
                            got = find_contiguous_block(
                                dest_free[o], need, states[o].topo
                            )
                            if got is not None:
                                dest_free[o] -= set(got[0])
                                plan.append(Migration(victim.name, name, o))
                                placed = True
                                break
                        if not placed:
                            feasible = False
                            break
                    if feasible:
                        return plan
        return None

    def _defrag_plan_tree(
        self, cards: int, max_migrations: int
    ) -> Optional[List["Migration"]]:
        """Tree-node (GPU) defrag: open *cards* free cards within one
        level-1 group by migrating the fewest GPU-only pods out of it; every
        migrated pod must provably re-place on another node's scalar free
        count (exact for tree fill — it spills structurally, never fails on
        shape). Returns []/plan/None with ``defrag_plan`` semantics."""
        free_by = {
            name: group_scheduler.free_cards_by_group(self.nodes[name].info, GPU.base)
            for name in utils.sorted_string_keys(self.nodes)
            if name not in self.cordoned  # same contract as the TPU plan
        }
        for name, groups in free_by.items():
            if any(len(keys) >= cards for keys in groups.values()):
                return []  # some group already holds a full block

        for name in utils.sorted_string_keys(free_by):
            node = self.nodes[name]
            # victims by group: GPU-only pods holding cards in that group,
            # largest in-group holdings first (fewest migrations)
            holders_by_group: Dict[str, List[tuple]] = {}
            group_capacity: Dict[str, int] = {
                g: len(keys) for g, keys in free_by[name].items()
            }
            for p in sorted(node.pods.values(), key=lambda p: p.name):
                if group_scheduler.held_cards(p, TPU.base):
                    continue  # mixed/TPU pod: not a tree-defrag victim
                by_g: Dict[str, int] = {}
                for key in group_scheduler.held_cards(p, GPU.base):
                    g = group_scheduler.cards_group(key)
                    if g is not None:
                        by_g[g] = by_g.get(g, 0) + 1
                for g, cnt in by_g.items():
                    holders_by_group.setdefault(g, []).append((p, cnt))
                    group_capacity[g] = group_capacity.get(g, 0) + cnt
            for g in utils.sorted_string_keys(group_capacity):
                if group_capacity[g] < cards:
                    continue  # group too small even fully vacated
                free_g = len(free_by[name].get(g, []))
                holders = sorted(
                    holders_by_group.get(g, []), key=lambda t: (-t[1], t[0].name)
                )
                chosen: List[PodInfo] = []
                got = free_g
                for p, cnt in holders:
                    if got >= cards or len(chosen) >= max_migrations:
                        break
                    chosen.append(p)
                    got += cnt
                if got < cards or not chosen:
                    continue
                # Re-placement feasibility on scalar free counts. The source
                # node itself is a valid destination (mirroring the TPU
                # plan's "back onto the source node outside the opened
                # block"): after vacating the chosen pods and giving *cards*
                # to the block, it has free = current + freed - cards
                # (execute_defrag places the pending pod first, so re-placed
                # victims cannot re-take the opened group).
                freed = sum(
                    len(group_scheduler.held_cards(p, GPU.base)) for p in chosen
                )
                dest_free = {
                    o: self.nodes[o].info.allocatable.get(GPU.resource_name, 0)
                    for o in utils.sorted_string_keys(self.nodes)
                    if o != name
                }
                dest_free[name] = (
                    self.nodes[name].info.allocatable.get(GPU.resource_name, 0)
                    + freed
                    - cards
                )
                plan: List[Migration] = []
                feasible = True
                for p in chosen:
                    need = len(group_scheduler.held_cards(p, GPU.base))
                    placed = False
                    for o in utils.sorted_string_keys(dest_free):
                        if dest_free[o] >= need:
                            dest_free[o] -= need
                            plan.append(Migration(p.name, name, o))
                            placed = True
                            break
                    if not placed:
                        feasible = False
                        break
                if feasible:
                    return plan
        return None

    def execute_defrag(
        self, plan: List["Migration"], pending: Optional[PodInfo] = None
    ) -> Tuple[List[PodInfo], Optional[PodInfo]]:
        """Apply a defrag plan: release every migrating pod, place the
        *pending* pod the plan was computed for (it takes the opened perfect
        block — placing it first is what stops re-placed victims from
        re-fragmenting the region), then re-place the victims (planned
        destination first, anywhere as fallback). Returns
        (moved victims, placed pending pod or None).

        Rollback: if anything fails mid-way, every released pod is restored
        and any partial placements are released before the error propagates
        — no pod is ever dropped."""
        originals: List[Tuple[Migration, PodInfo]] = []
        for mig in plan:
            pod = self.nodes[mig.from_node].pods[mig.pod_name]
            originals.append((mig, _reset_for_reschedule(pod)))
            self.release(mig.pod_name)

        placed_pending: Optional[PodInfo] = None
        moved: List[PodInfo] = []
        try:
            if pending is not None:
                if plan:
                    # Pin the pending pod to the node the plan opened the
                    # block on: the TPU score (placement contiguity) makes
                    # that node win naturally, but the tree (GPU) score is
                    # free-locality-blind — unpinned, the pod could land
                    # split across sockets on another node and the victim's
                    # fallback could re-take the opened group.
                    src = plan[0].from_node
                    try:
                        placed_pending = self.schedule(
                            pending, candidates=[src]
                        )
                    except SchedulingError:
                        placed_pending = self.schedule(pending)
                else:
                    placed_pending = self.schedule(pending)
            for mig, fresh in originals:
                try:
                    moved.append(
                        self.schedule(fresh, candidates=[mig.to_node])
                    )
                except SchedulingError:
                    moved.append(self.schedule(fresh))  # anywhere fallback
            return moved, placed_pending
        except SchedulingError as exc:
            for p in moved:
                self.release(p.name)
            if placed_pending is not None:
                self.release(placed_pending.name)
            # Restore each original to its source node, falling back to an
            # unpinned placement if cluster state changed concurrently; an
            # irrecoverable pod is surfaced in the raised error, never
            # silently dropped.
            lost: List[PodInfo] = []
            for mig, fresh in originals:
                lost.extend(self._restore_pods([fresh], mig.from_node))
            if lost:
                utils.errorf(
                    "defrag execution failed; pods %s could not be restored",
                    [p.name for p in lost],
                )
                raise SchedulingError(
                    f"defrag rollback could not restore pods "
                    f"{[p.name for p in lost]} (cause: {exc})"
                ) from exc
            utils.errorf("defrag execution failed; all pods restored")
            raise

    # -- failure handling / elastic recovery ---------------------------------

    def fail_node(self, name: str) -> List[PodInfo]:
        """Handle a node failure: deregister the node and return the pods it
        was running, reset to schedulable form (placement artifacts
        stripped), for rescheduling elsewhere.

        The reference's failure story stops at graceful degradation inside
        one node (probe failure -> zero devices, nvidia_gpu_manager.go:
        191-197); cross-node recovery was the external core's job, so
        kubetpu implements it: callers re-submit the returned pods via
        ``schedule``/``schedule_gang`` (all state is reconstructable, there
        is nothing else to clean up — SURVEY.md §5.3-5.4).
        """
        node = self.nodes.get(name)
        if node is None:
            return []
        evicted = [_reset_for_reschedule(pod) for pod in node.pods.values()]
        self.remove_node(name)
        utils.logf(0, "node %s failed; %d pods evicted for rescheduling", name, len(evicted))
        self._event("node_failed", node=name, evicted=[p.name for p in evicted])
        return evicted

    # -- introspection ------------------------------------------------------

    def check_invariants(self) -> List[str]:
        """Audit the accounting invariants every scheduling path must
        preserve; returns human-readable violations (empty = consistent).
        The chaos soak's oracle: after a run of injected drops/retries/
        evictions there must be NO double allocation —

        - a pod name is placed on at most one node;
        - a per-chip cards key is held by at most one POD (a pod's init
          containers deliberately REUSE its running containers' pool, so
          holds are the pod's distinct-key set — mirroring
          group_scheduler._account), and held + free == capacity for
          every advertised cards key;
        - scalar device counts (tpu/gpu) balance: allocatable ==
          capacity - held cards of that class, within [0, capacity];
        - fractional (Round-18 vChip) holds balance per chip: the sum of
          co-located pods' milli shares + the advertised free milli ==
          MILLI_PER_CHIP (so Σ fractions on a chip <= 1.0 by
          construction, free >= 0 enforced), a fractionally-occupied
          chip's cards key is never ALSO whole-held, and every placed
          fractional pod actually holds exactly one /milli key.
        """
        problems: List[str] = []
        owner: Dict[str, str] = {}
        for name in utils.sorted_string_keys(self.nodes):
            node = self.nodes[name]
            held_keys: Dict[str, int] = {}
            held_millis: Dict[str, int] = {}
            scalar_held = {ResourceTPU: 0, ResourceGPU: 0}
            for pname, pod in node.pods.items():
                if pname in owner:
                    problems.append(
                        f"pod {pname!r} placed on both {owner[pname]!r} "
                        f"and {name!r}"
                    )
                owner[pname] = name
                try:
                    pod_frac = meshstate.pod_milli(pod)
                except ValueError as e:
                    problems.append(f"{name}/{pname}: {e}")
                    pod_frac = 0
                frac_holds = 0
                for key in group_scheduler._pod_held_keys(pod):
                    mm = group_scheduler._MILLI_KEY_RE.match(key)
                    if mm:
                        frac_holds += 1
                        held_millis[key] = (
                            held_millis.get(key, 0) + pod_frac
                        )
                        continue
                    m = group_scheduler._CARDS_KEY_RE.match(key)
                    if not m:
                        continue
                    held_keys[key] = held_keys.get(key, 0) + 1
                    scalar = group_scheduler._SCALAR_BY_BASE.get(m.group(5))
                    if scalar in scalar_held:
                        scalar_held[scalar] += 1
                if pod_frac > 0 and frac_holds != 1:
                    problems.append(
                        f"{name}: fractional pod {pname!r} holds "
                        f"{frac_holds} /milli keys (want exactly 1)"
                    )
            for key, n in sorted(held_keys.items()):
                if n > 1:
                    problems.append(
                        f"{name}: resource {key!r} held by {n} pods"
                    )
            # sweep EVERY per-device key the node advertises, not just the
            # currently-held ones — a key leaked while free (held 0 but
            # allocatable corrupted downward) must not hide from the audit
            for key in sorted(node.info.capacity):
                if key.endswith("/milli"):
                    held = held_millis.get(key, 0)
                    cap = int(node.info.capacity.get(key, 0))
                    free = int(node.info.allocatable.get(key, 0))
                    if not 0 <= free <= cap or held + free != cap:
                        problems.append(
                            f"{name}: {key!r} held({held}) + free({free}) "
                            f"!= capacity({cap})"
                        )
                    cards_key = key[: -len("/milli")] + "/cards"
                    if held > 0 and held_keys.get(cards_key, 0) > 0:
                        problems.append(
                            f"{name}: chip {cards_key!r} is whole-held "
                            f"AND carries {held} fractional milli"
                        )
                    continue
                if not key.endswith("/cards"):
                    continue
                n = held_keys.get(key, 0)
                cap = int(node.info.capacity.get(key, 0))
                free = int(node.info.allocatable.get(key, 0))
                if n + free != cap:
                    problems.append(
                        f"{name}: {key!r} held({n}) + free({free}) != "
                        f"capacity({cap})"
                    )
            for scalar, n in scalar_held.items():
                if scalar not in node.info.capacity:
                    continue
                cap = int(node.info.capacity.get(scalar, 0))
                free = int(node.info.allocatable.get(scalar, 0))
                if not 0 <= free <= cap or n + free != cap:
                    problems.append(
                        f"{name}: {scalar} held({n}) + free({free}) != "
                        f"capacity({cap})"
                    )
        # Round-21: the O(1) pod map must mirror node.pods exactly — a
        # drifted map silently degrades release/allocate to the fallback
        # sweep (still correct, but the drift itself is a bug) ...
        for pname, nname in sorted(self._pod_node.items()):
            if nname not in self.nodes or pname not in self.nodes[nname].pods:
                problems.append(
                    f"pod map: {pname!r} -> {nname!r} but the pod is not "
                    f"placed there"
                )
        for name in utils.sorted_string_keys(self.nodes):
            for pname in self.nodes[name].pods:
                if self._pod_node.get(pname) != name:
                    problems.append(
                        f"pod map: placed pod {pname!r} on {name!r} missing "
                        f"from the map"
                    )
        # ... and the fit index must agree with the advertised books (a
        # desynced index is caught HERE even though the schedule path
        # would survive it via the fallback sweep).
        if self.use_fit_index:
            problems.extend(
                self.fit_index.audit(
                    {n: self.nodes[n].info.allocatable for n in self.nodes}
                )
            )
        return problems

    def status(self) -> Dict[str, object]:
        """Operator-facing snapshot: per-node free/total devices and pods,
        per-slice free chips, and scheduling latency percentiles."""
        nodes = {}
        for name in utils.sorted_string_keys(self.nodes):
            node = self.nodes[name]
            state = meshstate.parse_mesh_state(node.info.allocatable)
            entry: Dict[str, object] = {
                "pods": sorted(node.pods),
            }
            if name in self.cordoned:
                entry["cordoned"] = True
            for scalar in (ResourceTPU, ResourceGPU):
                if scalar in node.info.capacity:
                    entry[scalar] = {
                        "free": node.info.allocatable.get(scalar, 0),
                        "total": node.info.capacity.get(scalar, 0),
                    }
            if state is not None:
                entry["slice"] = state.slice_name
                entry["host_index"] = state.host_index
                entry["free_chips"] = len(state.free)
                if state.milli_key:
                    # Round-18 fragmentation view: chips carrying
                    # fractional occupants AND free milli (a fully-packed
                    # chip strands nothing, so it isn't fragmentation —
                    # same definition as the obs CLI's frag line over the
                    # occupancy gauges), plus the milli they have left
                    entry["frac_partial_chips"] = sum(
                        1 for f in state.frac_free.values()
                        if 0 < f < meshstate.MILLI_PER_CHIP
                    )
                    entry["free_milli"] = state.free_milli()
            nodes[name] = entry
        slices: Dict[str, int] = {}
        for entry in nodes.values():
            if "slice" in entry:
                slices[entry["slice"]] = slices.get(entry["slice"], 0) + entry["free_chips"]
        return {
            "nodes": nodes,
            "slices_free_chips": slices,
            "latency": self.metrics.summary(),
            "fit_index": dict(self.index_stats, enabled=self.use_fit_index,
                              **self.fit_index.stats),
            "recent_events": self.events[-20:],
        }

    def pod_chip_coords(self, pod: PodInfo):
        """The global torus coordinates of a placed pod's chips (and the
        slice topology) — the bridge input for ``jobs.mesh_from_allocation``.
        Resolves the node via the O(1) pod map when the pod is live there
        (authoritative for placed pods), falling back to the pod's own
        node_name stamp for snapshots/copies."""
        node = self.nodes[self._pod_node.get(pod.name, pod.node_name)]
        state = meshstate.parse_mesh_state(node.info.capacity)
        if state is None:
            return None, []
        coords = []
        for cont in pod.running_containers.values():
            for to_key in cont.allocate_from.values():
                m = meshstate.CHIP_CARDS_RE.match(to_key)
                if m:
                    local = int(m.group(1))
                    if local in state.chip_coord:
                        coords.append(state.chip_coord[local])
        return state.topo, sorted(coords)

    def pod_vchip(self, pod: PodInfo):
        """A placed fractional pod's (topology, chip coordinate, milli
        share) — or (None, None, 0) for whole-chip / unplaced pods. The
        vChip sibling of ``pod_chip_coords``."""
        milli = meshstate.pod_milli(pod)
        node = self.nodes.get(self._pod_node.get(pod.name, pod.node_name))
        if milli == 0 or node is None:
            return None, None, 0
        state = meshstate.parse_mesh_state(node.info.capacity)
        if state is None:
            return None, None, 0
        # held_milli is THE "which /milli key does this pod hold" scan
        # (shared with the packing oracle and preemption) — one grammar,
        # one implementation
        for key in group_scheduler.held_milli(pod):
            m = meshstate.CHIP_MILLI_RE.match(key)
            local = int(m.group(1)) if m else -1
            if local in state.chip_coord:
                return state.topo, state.chip_coord[local], milli
        return None, None, 0

    def chip_occupancy(
        self, nodes: Optional[Sequence[str]] = None
    ) -> Dict[str, Dict[int, float]]:
        """node -> local chip id -> occupancy fraction in [0, 1], for
        every vChip-capable chip: 1.0 when the chip is whole-held,
        otherwise (MILLI_PER_CHIP - free milli) / MILLI_PER_CHIP. Feeds
        the ``kubetpu_chip_occupancy_frac{node,chip}`` gauges and the
        obs CLI's fragmentation line. *nodes* scopes the sweep (the
        submit hot path asks only about the nodes it touched)."""
        out: Dict[str, Dict[int, float]] = {}
        names = (utils.sorted_string_keys(self.nodes) if nodes is None
                 else [n for n in sorted(nodes) if n in self.nodes])
        for name in names:
            node = self.nodes[name]
            st = meshstate.parse_mesh_state(node.info.allocatable)
            if st is None or not st.milli_key:
                continue
            per: Dict[int, float] = {}
            for local, mkey in sorted(st.milli_key.items()):
                cards_key = st.chip_key.get(local, "")
                if node.info.allocatable.get(cards_key, 0) < 1:
                    per[local] = 1.0
                    continue
                free = node.info.allocatable.get(
                    mkey, meshstate.MILLI_PER_CHIP)
                per[local] = (
                    meshstate.MILLI_PER_CHIP - free
                ) / float(meshstate.MILLI_PER_CHIP)
            out[name] = per
        return out

    def gang_slice_contiguity(self, pods: Sequence[PodInfo]) -> Dict[str, float]:
        """Per-slice ICI-contiguity of a placed gang's chips: slice name ->
        contiguity of the members placed on that slice. Coordinates are
        only comparable WITHIN a slice (cross-slice hops are DCN, not ICI),
        so a multislice gang is scored slice by slice."""
        per: Dict[str, Tuple[TpuTopology, list]] = {}
        for pod in pods:
            pod_topo, pod_coords = self.pod_chip_coords(pod)
            if pod_topo is None or not pod_coords:
                continue
            state = meshstate.parse_mesh_state(
                self.nodes[pod.node_name].info.capacity
            )
            key = state.slice_name if state is not None else pod_topo.name
            per.setdefault(key, (pod_topo, []))[1].extend(pod_coords)
        return {s: contiguity_score(c, t) for s, (t, c) in sorted(per.items())}

    def gang_contiguity(self, pods: Sequence[PodInfo]) -> float:
        """ICI-contiguity of a placed gang — the BASELINE 'ICI-contiguity
        score' metric. For a multislice gang this is the MINIMUM per-slice
        score (the weakest sub-gang bounds the job's collective locality);
        for the single-slice case it is exactly the whole-gang score."""
        per = self.gang_slice_contiguity(pods)
        if not per:
            return 0.0
        return min(per.values())
