"""The KubeDevice-core stand-in (SURVEY.md §7 step 6): scheduling loop,
group/gang scheduler with AllocateFrom fill, usage accounting, latency
metrics. The reference delegates all of this to the external
github.com/Microsoft/KubeDevice repo; kubetpu ships it."""

from kubetpu.core.cluster import Cluster, ClusterNode, SchedulingError
from kubetpu.core.group_scheduler import (
    fill_allocate_from,
    return_pod_resources,
    take_pod_resources,
)
from kubetpu.core.journal import Journal, JournalCorrupt
from kubetpu.core.metrics import LatencyRecorder

__all__ = [
    "Cluster",
    "ClusterNode",
    "SchedulingError",
    "Journal",
    "JournalCorrupt",
    "fill_allocate_from",
    "return_pod_resources",
    "take_pod_resources",
    "LatencyRecorder",
]
