"""Scheduling latency metrics.

The reference has no tracing/profiling hooks (SURVEY.md §5.1); kubetpu adds
latency histograms around the per-pod scheduling hot path because the
BASELINE north-star metric is pod-schedule p50 < 100 ms for 256-chip gangs.
"""

from __future__ import annotations

import threading
from typing import Dict, List


class LatencyRecorder:
    """Collects per-operation latencies (seconds) and reports percentiles."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._samples: Dict[str, List[float]] = {}

    def record(self, op: str, seconds: float) -> None:
        with self._lock:
            self._samples.setdefault(op, []).append(seconds)

    def count(self, op: str) -> int:
        with self._lock:
            return len(self._samples.get(op, []))

    def percentile(self, op: str, p: float) -> float:
        """p in [0, 100]; returns seconds (0.0 if no samples)."""
        with self._lock:
            samples = sorted(self._samples.get(op, []))
        if not samples:
            return 0.0
        idx = min(len(samples) - 1, max(0, int(round(p / 100.0 * (len(samples) - 1)))))
        return samples[idx]

    def summary(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            ops = list(self._samples)
        return {
            op: {
                "count": self.count(op),
                "p50_ms": self.percentile(op, 50) * 1e3,
                "p99_ms": self.percentile(op, 99) * 1e3,
            }
            for op in ops
        }
