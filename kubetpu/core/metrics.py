"""Scheduling latency metrics.

The reference has no tracing/profiling hooks (SURVEY.md §5.1); kubetpu adds
latency histograms around the per-pod scheduling hot path because the
BASELINE north-star metric is pod-schedule p50 < 100 ms for 256-chip gangs.

Round-8: ``LatencyRecorder`` is now a thin facade over
``kubetpu.obs.Histogram`` — one bounded reservoir per op instead of the
old unbounded per-op sample lists, so a controller that schedules for
months holds at most ``cap`` samples per op. Percentiles are EXACT below
the cap; above it, uniform reservoir sampling keeps every observation
with equal probability (cap/count), making the reported quantiles
unbiased estimates (error shrinks as cap grows) while ``count`` stays
exact. ``bind(registry, metric)`` re-homes the per-op histograms into an
``obs.Registry`` (label ``op=<op>``), which is how the controller's
``/metrics`` exports ``kubetpu_schedule_latency_seconds`` without a
second recording path.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from kubetpu.obs.registry import Histogram, Registry

# per-op reservoir size: exact percentiles for the first 4096 samples of
# each op, unbiased estimates beyond
DEFAULT_CAP = 4096


class LatencyRecorder:
    """Collects per-operation latencies (seconds) and reports percentiles.

    Memory is bounded: each op holds one fixed-size reservoir (``cap``
    samples), never a growing list."""

    def __init__(self, cap: int = DEFAULT_CAP,
                 registry: Optional[Registry] = None,
                 metric: str = "kubetpu_latency_seconds") -> None:
        self._lock = threading.Lock()
        self._cap = cap
        self._hists: Dict[str, Histogram] = {}
        self._registry = registry
        self._metric = metric

    def bind(self, registry: Registry, metric: str) -> "LatencyRecorder":
        """Export this recorder's histograms through *registry* as
        ``<metric>{op="<op>"}`` summaries — existing ops are attached
        in place (samples kept), future ops register on first record.
        Returns self for chaining."""
        with self._lock:
            self._registry = registry
            self._metric = metric
            for op, hist in self._hists.items():
                # facade: the name is the literal bind() callers pass,
                # validated by the registry at registration
                # ktlint: disable=KTP004
                registry.attach_histogram(metric, hist, op=op)
        return self

    def _hist(self, op: str) -> Histogram:
        with self._lock:
            hist = self._hists.get(op)
            if hist is None:
                if self._registry is not None:
                    # facade: forwards the bind()-time literal name
                    # ktlint: disable=KTP004
                    hist = self._registry.histogram(
                        self._metric, cap=self._cap, op=op)
                else:
                    hist = Histogram(cap=self._cap)
                self._hists[op] = hist
            return hist

    def record(self, op: str, seconds: float) -> None:
        self._hist(op).observe(seconds)

    def count(self, op: str) -> int:
        with self._lock:
            hist = self._hists.get(op)
        return hist.count if hist is not None else 0

    def percentile(self, op: str, p: float) -> float:
        """p in [0, 100]; returns seconds (0.0 if no samples)."""
        with self._lock:
            hist = self._hists.get(op)
        return hist.percentile(p) if hist is not None else 0.0

    def recent_percentile(self, op: str, p: float,
                          window: int = 128) -> float:
        """Percentile over the LAST *window* observations (seconds; 0.0
        with no samples) — the recovery-capable read a live control
        signal needs. The lifetime reservoir never forgets an incident
        (a burst's p99 stays elevated for hours after traffic
        normalizes — the windowed-percentile lesson the SLO engine
        bakes in), so anything that FEEDS BACK into decisions (the
        Round-14 autoscaler's hot signal via ``load_info``) must read a
        recent window. Exact while the reservoir is below cap (the
        buffer is an append-only log there); past cap it degrades to
        the full-reservoir estimate — slow-moving, never latched."""
        with self._lock:
            hist = self._hists.get(op)
        if hist is None:
            return 0.0
        count, buf = hist.tail()
        if not buf:
            return 0.0
        recent = sorted(buf[-window:] if count <= len(buf) else buf)
        idx = min(len(recent) - 1,
                  max(0, int(round(p / 100.0 * (len(recent) - 1)))))
        return recent[idx]

    def summary(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            ops = list(self._hists)
        return {
            op: {
                "count": self.count(op),
                "p50_ms": self.percentile(op, 50) * 1e3,
                "p90_ms": self.percentile(op, 90) * 1e3,
                "p99_ms": self.percentile(op, 99) * 1e3,
            }
            for op in ops
        }
