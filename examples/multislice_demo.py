"""Multislice end to end: a DCN-spanning gang through scheduler -> env ->
dcn-axis mesh -> training step.

The round-5 capability walkthrough: two fragmented v5e-64 slices cannot
host an 8-host gang alone, so the `kubetpu/multislice: 2` knob splits it
into two 4-host sub-gangs (per-slice contiguity 1.0); Allocate injects
the MEGASCALE identity; the job side builds the matching
{dcn: 2, sp, tp} mesh (slice axis outermost — only the gradient
all-reduce crosses DCN) and runs a training step whose loss exactly
matches the single-mesh data-parallel equivalent.

    python examples/multislice_demo.py      # CPU, 8 virtual devices
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from kubetpu.api.types import ContainerInfo, PodInfo  # noqa: E402
from kubetpu.core import Cluster  # noqa: E402
from kubetpu.device import (  # noqa: E402
    make_fake_tpus_info,
    new_fake_tpu_dev_manager,
)
from kubetpu.jobs import (  # noqa: E402
    ModelConfig,
    init_state,
    make_mesh,
    make_multislice_mesh,
    make_train_step,
)
from kubetpu.plugintypes import ResourceTPU  # noqa: E402
from kubetpu.scheduler.meshstate import MultisliceKey  # noqa: E402


def main():
    # -- control plane: place a DCN-spanning gang -------------------------
    cluster = Cluster()
    for uid, prefix in (("podA", "a"), ("podB", "b")):
        for h in range(4):
            cluster.register_node(
                f"{prefix}{h}",
                device=new_fake_tpu_dev_manager(
                    make_fake_tpus_info("v5e-64", host_index=h,
                                        slice_uid=uid)
                ),
            )
    pods = [
        PodInfo(
            name=f"w{i}",
            requests={MultisliceKey: 2},
            running_containers={
                "main": ContainerInfo(requests={ResourceTPU: 8})
            },
        )
        for i in range(8)
    ]
    placed = cluster.schedule_gang(pods)
    per = cluster.gang_slice_contiguity(placed)
    print(f"gang of 8 placed across {len(per)} slices, "
          f"per-slice contiguity {per}")
    env0 = cluster.allocate(placed[0].name)["main"][2]
    env4 = cluster.allocate(placed[4].name)["main"][2]
    print(f"  worker 0 env: MEGASCALE_NUM_SLICES={env0['MEGASCALE_NUM_SLICES']} "
          f"SLICE_ID={env0['MEGASCALE_SLICE_ID']}")
    print(f"  worker 4 env: MEGASCALE_NUM_SLICES={env4['MEGASCALE_NUM_SLICES']} "
          f"SLICE_ID={env4['MEGASCALE_SLICE_ID']}")

    # -- job side: the matching dcn-axis mesh -----------------------------
    n_slices = int(env0["MEGASCALE_NUM_SLICES"])
    cfg = ModelConfig(vocab=128, d_model=64, n_layers=2, n_heads=4,
                      d_ff=128, max_seq=128)
    mesh = make_multislice_mesh({"dcn": n_slices, "dp": 1, "sp": 2, "tp": 2})
    print(f"mesh axes: {dict(mesh.shape)} (dcn outermost = DCN boundary)")
    state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh, optimizer=opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                cfg.vocab, jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    state, loss = step(state, tokens, targets)

    # identity check: dcn and dp are both pure data axes
    ref_mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    ref_state, ref_opt = init_state(jax.random.PRNGKey(0), cfg, ref_mesh)
    ref_step = make_train_step(cfg, ref_mesh, optimizer=ref_opt)
    _, ref_loss = ref_step(ref_state, tokens, targets)
    print(f"multislice loss {float(loss):.6f} == "
          f"single-slice dp loss {float(ref_loss):.6f}")
    assert abs(float(loss) - float(ref_loss)) < 1e-4
    print("multislice demo OK")


if __name__ == "__main__":
    main()
