#!/usr/bin/env python3
"""The full kubetpu story in one script: schedule -> allocate -> mesh ->
train -> checkpoint -> fail a node -> reschedule -> resume.

A gang job is placed on a fake v5e-64 slice by the topology-aware scheduler,
the allocation's torus coordinates become a ``jax.sharding.Mesh``, a sharded
training job runs and checkpoints, then a host "fails": the scheduler
evicts and re-places the worker, and training resumes from the checkpoint
on the new allocation — the elastic loop the framework exists to serve.

Runs anywhere (fake devices; JAX on an 8-device virtual CPU mesh):

    python examples/train_demo.py          # in-process cluster
    python examples/train_demo.py --wire   # 8 REAL agent processes; the
                                           # "node failure" is a SIGKILLed
                                           # agent detected over the wire
"""

import json
import os
import signal
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

from kubetpu.api.types import ContainerInfo, PodInfo  # noqa: E402
from kubetpu.core import Cluster  # noqa: E402
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager  # noqa: E402
from kubetpu.plugintypes import ResourceTPU  # noqa: E402


def pod(name, chips):
    return PodInfo(
        name=name,
        running_containers={"main": ContainerInfo(requests={ResourceTPU: chips})},
    )


def allocation_coords(cluster, placed):
    """The torus coordinates a placed pod's chips landed on."""
    _topo, coords = cluster.pod_chip_coords(placed)
    return coords


def spawn_agents(n):
    """Start n agent processes concurrently, then collect their hello
    lines (startup overlaps; a dead agent's stderr is surfaced)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "kubetpu.cli.agent", "--serve",
             "--fake", "v5e-64", "--host", str(h), "--port", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, cwd=repo, text=True,
        )
        for h in range(n)
    ]
    agents = []
    for proc in procs:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(
                f"agent pid {proc.pid} died during startup:\n"
                + (proc.stderr.read() or "(no stderr)")
            )
        hello = json.loads(line)
        agents.append((proc, hello["listening"], hello["node"]))
    return agents


def main(wire: bool = False):
    # --- 1. a v5e-64 slice: 8 host-nodes (in-process fakes, or REAL agent
    # processes reached over the HTTP wire) ------------------------------
    cluster = Cluster()
    agents = []
    if wire:
        agents = spawn_agents(8)
        for _proc, url, _name in agents:
            cluster.register_remote_node(url)
        print(f"cluster: {len(cluster.nodes)} hosts x 8 chips (v5e-64), "
              f"served by {len(agents)} live agent processes")
    else:
        for h in range(8):
            cluster.register_node(
                f"host{h}",
                device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-64", host_index=h)),
            )
        print(f"cluster: {len(cluster.nodes)} hosts x 8 chips (v5e-64)")

    try:
        _run_demo(cluster, agents, wire)
    finally:
        for proc, _u, _n in agents:
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=10)


def _run_demo(cluster, agents, wire):
    # --- 2. schedule one 8-chip worker, ICI-contiguous -------------------
    placed = cluster.schedule(pod("trainer", 8))
    _, devices, env = cluster.allocate("trainer")["main"]
    coords = allocation_coords(cluster, placed)
    print(f"placed on {placed.node_name}: devices={devices[:2]}..., "
          f"TPU_VISIBLE_DEVICES={env['TPU_VISIBLE_DEVICES']}, coords={coords}")

    # --- 3. the allocation becomes a jax mesh; train + checkpoint --------
    import jax

    # the environment may pin JAX to a hardware platform via sitecustomize;
    # this demo is a CPU-mesh walkthrough (same pattern as tests/conftest)
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from kubetpu.jobs import ModelConfig, init_state, make_train_step, mesh_from_allocation
    from kubetpu.jobs.checkpoint import restore_checkpoint, save_checkpoint
    from kubetpu.jobs.data import SyntheticCorpus, prefetch_to_mesh
    from kubetpu.jobs.train import make_optimizer

    mesh = mesh_from_allocation(coords, {"dp": 2, "sp": 2, "tp": 2})
    print(f"mesh from allocation: {dict(zip(mesh.axis_names, mesh.devices.shape))}")

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
    opt = make_optimizer(lr=5e-3)
    state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh, optimizer=opt)
    step = make_train_step(cfg, mesh, optimizer=opt)
    corpus = SyntheticCorpus(vocab=cfg.vocab)
    batches = prefetch_to_mesh((b for _, b in zip(range(10), corpus.batches(8, 32))), mesh)
    for tokens, targets in batches:
        state, loss = step(state, tokens, targets)
    print(f"trained 10 steps, loss {float(loss):.3f}")

    ckpt_dir = tempfile.mkdtemp(prefix="kubetpu-demo-")
    save_checkpoint(os.path.join(ckpt_dir, str(int(state.step))), state)
    print(f"checkpointed step {int(state.step)} -> {ckpt_dir}")

    # --- 4. the host fails; reschedule and resume ------------------------
    if wire:
        victim = next(p for p, _u, n in agents if n == placed.node_name)
        print(f"SIGKILL agent of {placed.node_name} (pid {victim.pid})")
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=10)
        evicted = cluster.poll_remote_nodes()[placed.node_name]
    else:
        evicted = cluster.fail_node(placed.node_name)
    replaced = cluster.schedule(evicted[0])
    new_coords = allocation_coords(cluster, replaced)
    print(f"host failed; rescheduled onto {replaced.node_name}, coords={new_coords}")

    new_mesh = mesh_from_allocation(new_coords, {"dp": 2, "sp": 2, "tp": 2})
    fresh, opt = init_state(jax.random.PRNGKey(1), cfg, new_mesh, optimizer=make_optimizer(lr=5e-3))
    resumed = restore_checkpoint(os.path.join(ckpt_dir, "10"), fresh)
    step2 = make_train_step(cfg, new_mesh, optimizer=opt)
    for tokens, targets in prefetch_to_mesh(
        (b for _, b in zip(range(5), corpus.batches(8, 32, seed=1))), new_mesh
    ):
        resumed, loss = step2(resumed, tokens, targets)
    print(f"resumed from step 10 on the new allocation -> step {int(resumed.step)}, "
          f"loss {float(loss):.3f}")
    print("demo OK")


if __name__ == "__main__":
    main(wire="--wire" in sys.argv[1:])
