"""The round-4 model-lifecycle walkthrough: import a HuggingFace llama
checkpoint, fine-tune two LoRA adapters on different data, and serve BOTH
tenants concurrently on one base model (multi-LoRA continuous batching).

    python examples/finetune_serve_demo.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if __name__ == "__main__":
    # the environment may pin JAX to a hardware platform via sitecustomize;
    # this demo is a CPU walkthrough (same pattern as tests/conftest)
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from kubetpu.jobs import make_mesh  # noqa: E402
from kubetpu.jobs.lora import (  # noqa: E402
    LoraConfig,
    init_lora_state,
    make_lora_train_step,
    merge_lora,
)
from kubetpu.jobs.multi_lora import (  # noqa: E402
    MultiLoraDecodeServer,
    stack_adapters,
)
from kubetpu.jobs.train import make_optimizer  # noqa: E402


def main():
    # 1. a "pretrained" base checkpoint — a tiny random HF llama here, a
    # real repo checkpoint in practice (params_from_hf is layout-only)
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from kubetpu.jobs.hf_import import params_from_hf

    torch.manual_seed(0)
    hf = LlamaForCausalLM(LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-6,
        attention_bias=False, mlp_bias=False,
    )).eval()
    base, cfg = params_from_hf(hf)
    print(f"imported HF llama: {cfg.n_layers}L d{cfg.d_model} "
          f"GQA kv={cfg.kv_heads}")

    # 2. fine-tune one LoRA adapter per tenant (adapter ~ = the tenant's
    # task; here: memorize a tenant-specific sequence)
    lcfg = LoraConfig(rank=4, alpha=8.0)
    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1},
                     devices=jax.devices()[:1])
    adapters = []
    for tenant in range(2):
        state, opt = init_lora_state(
            jax.random.PRNGKey(tenant + 1), cfg, lcfg, mesh,
            optimizer=make_optimizer(lr=2e-2))
        step = make_lora_train_step(cfg, lcfg, mesh, optimizer=opt)
        data = jax.random.randint(
            jax.random.PRNGKey(10 + tenant), (4, 16), 1, cfg.vocab)
        first = last = None
        for _ in range(15):
            state, loss = step(state, base, data, jnp.roll(data, -1, 1))
            first = first if first is not None else float(loss)
            last = float(loss)
        adapters.append(state.params)
        print(f"tenant {tenant}: lora fine-tune loss "
              f"{first:.3f} -> {last:.3f} "
              f"({sum(x.size for x in jax.tree.leaves(state.params))} "
              f"adapter params)")

    # 3. serve both tenants in ONE batch on ONE base model
    stack = stack_adapters(lcfg, adapters)
    server = MultiLoraDecodeServer(cfg, base, lcfg, stack, n_slots=2,
                                   max_seq=64, max_new_tokens=8,
                                   eos_id=None)
    server.warmup()
    prompt = [1, 5, 9]
    r0 = server.submit(prompt, adapter=0)
    r1 = server.submit(prompt, adapter=1)  # same prompt, other tenant
    server.drain()
    out0, out1 = server.result(r0), server.result(r1)
    print(f"tenant 0 continuation: {out0[len(prompt):]}")
    print(f"tenant 1 continuation: {out1[len(prompt):]}")
    assert out0 != out1, "adapters must steer the outputs apart"

    # 4. exact single-tenant parity: merged export reproduces the stream
    from kubetpu.jobs.serving import DecodeServer

    ref = DecodeServer(cfg, merge_lora(base, adapters[1], lcfg), n_slots=1,
                       max_seq=64, max_new_tokens=8, eos_id=None)
    rr = ref.submit(prompt)
    ref.drain()
    assert ref.result(rr) == out1
    print("multi-tenant output == merged single-tenant output (exact)")


if __name__ == "__main__":
    main()
