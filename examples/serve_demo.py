"""Continuous-batching inference demo: requests stream through a fixed
slot batch, entering and leaving without stopping it.

    python examples/serve_demo.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if __name__ == "__main__":
    # the environment may pin JAX to a hardware platform via sitecustomize;
    # this demo is a CPU walkthrough (same pattern as tests/conftest)
    jax.config.update("jax_platforms", "cpu")

from kubetpu.jobs import ModelConfig, init_params  # noqa: E402
from kubetpu.jobs.serving import DecodeServer  # noqa: E402


def main():
    cfg = ModelConfig(vocab=256, d_model=64, n_layers=2, n_heads=4, d_ff=128)
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = DecodeServer(cfg, params, n_slots=2, max_seq=128, max_new_tokens=8)

    print("submit r0 (4-token prompt), r1 (2-token prompt)")
    r0 = server.submit([3, 14, 15, 9])
    r1 = server.submit([26, 5])
    rejected = server.submit([1, 2, 3])
    print(f"third request while full -> {rejected} (queued by the caller)")

    step = 0
    pending = [1, 2, 3]
    r2 = None
    while server.active.any() or r2 is None:
        toks = server.step()
        step += 1
        print(f"step {step}: {toks}")
        if r2 is None:
            r2 = server.submit(pending)  # admitted the moment a slot frees
            if r2 is not None:
                print(f"slot freed -> r2 admitted as request {r2}")
    server.drain()

    for rid in (r0, r1, r2):
        print(f"request {rid}: {server.pop_result(rid)}")

    # per-request sampling: one greedy, one nucleus-sampled — both decode
    # in the SAME compiled step, each slot under its own settings
    rg = server.submit([7, 7, 7, 7])
    rs = server.submit([7, 7, 7, 7],
                       sampling={"temperature": 1.0, "top_p": 0.9})
    server.drain()
    print(f"greedy    {rg}: {server.pop_result(rg)}")
    print(f"sampled   {rs}: {server.pop_result(rs)} (temperature 1.0, top-p 0.9)")

    # chunked prefill: a LONG prompt streams in 16 tokens per step next
    # to a live decode stream instead of freezing it for a monolithic
    # prefill — and the tokens are exactly the monolithic server's
    chunked = DecodeServer(cfg, params, n_slots=2, max_seq=128,
                           max_new_tokens=8, prefill_budget=16, overlap=True)
    short = chunked.submit([3, 14, 15, 9])
    long_rid = chunked.enqueue(list(range(2, 66)))   # 64 tokens, 4 chunks
    decoded_during_prefill = 0
    for _ in range(4):                               # the admission window
        out = chunked.step()
        decoded_during_prefill += len(out.get(short, []))
    chunked.drain()
    print(f"chunked prefill: short stream emitted {decoded_during_prefill} "
          f"tokens while the 64-token prompt streamed in")
    print(f"long request {long_rid}: {chunked.pop_result(long_rid)[-8:]}")
    print("serve demo OK")


if __name__ == "__main__":
    main()
