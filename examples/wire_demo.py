"""End-to-end demo of the agent wire: REAL agent processes, one control
plane, a gang scheduled over HTTP, a killed agent driving elastic recovery.

What happens (each step printed):
1. Spawn 4 ``kubetpu-agent --serve`` processes — one per v5e-64 host
   (fake probe), each on an ephemeral port.
2. The control plane registers them over the wire and gang-schedules a
   2-host x 8-chip job; AllocateFrom is filled control-plane-side, the
   container-start injection (``POST /allocate``) runs node-side where the
   devices live.
3. SIGKILL one gang member's agent. The next poll detects the dead node,
   evicts its pod, and the worker reschedules onto a surviving host.

This is the process topology the reference has (CRI shim / scheduler /
nvmlinfo as separate processes, SURVEY.md §3) with the transport leg the
reference left to the external KubeDevice core.

    python examples/wire_demo.py
"""

import json
import os
import signal
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubetpu.api.types import ContainerInfo, PodInfo  # noqa: E402
from kubetpu.core import Cluster  # noqa: E402
from kubetpu.plugintypes import ResourceTPU  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tpu_pod(name, chips):
    return PodInfo(
        name=name,
        running_containers={"main": ContainerInfo(requests={ResourceTPU: chips})},
    )


def spawn_agent(host_index):
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "kubetpu.cli.agent", "--serve",
            "--fake", "v5e-64", "--host", str(host_index), "--port", "0",
        ],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, cwd=REPO, text=True,
    )
    hello = json.loads(proc.stdout.readline())
    return proc, hello["listening"], hello["node"]


def main():
    agents = [spawn_agent(h) for h in range(4)]
    try:
        for _p, url, name in agents:
            print(f"agent up: {name} at {url}")

        cluster = Cluster()
        for _p, url, _n in agents:
            info = cluster.register_remote_node(url)
            print(f"registered {info.name}: {info.allocatable[ResourceTPU]} chips free")

        gang = [tpu_pod("w0", 8), tpu_pod("w1", 8)]
        placed = cluster.schedule_gang(gang)
        print(f"gang placed on {[p.node_name for p in placed]}, "
              f"contiguity={cluster.gang_contiguity(placed)}")
        for p in placed:
            _m, devices, env = cluster.allocate(p.name)["main"]
            print(f"  {p.name} on {p.node_name}: {len(devices)} devices, "
                  f"TPU_VISIBLE_DEVICES={env['TPU_VISIBLE_DEVICES']}")

        victim_node = placed[0].node_name
        victim = next(p for p, _u, n in agents if n == victim_node)
        print(f"\nSIGKILL agent of {victim_node} (pid {victim.pid})")
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=10)

        evicted = cluster.poll_remote_nodes()
        for node, pods in evicted.items():
            print(f"node {node} failed; evicted {[p.name for p in pods]}")
            for pod in pods:
                again = cluster.schedule(pod)
                _m, devices, env = cluster.allocate(again.name)["main"]
                print(f"  {again.name} rescheduled -> {again.node_name} "
                      f"({len(devices)} devices)")

        print("\nfinal status:")
        status = cluster.status()
        for name, entry in status["nodes"].items():
            print(f"  {name}: free_chips={entry.get('free_chips')} pods={entry['pods']}")
        print("wire demo OK")
        return 0
    finally:
        for p, _u, _n in agents:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10)


if __name__ == "__main__":
    sys.exit(main())
