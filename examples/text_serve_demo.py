"""Text-in / text-out serving demo: subword (BPE) tokenizer end to end.

The layer the HF import story completes: ``load_hf_tokenizer`` reads a
checkpoint's ``tokenizer.json`` (here: the checked-in fixture — the same
byte-level-BPE + llama-3-pretokenizer layout real Llama-3 checkpoints
ship), a model trains on tokenized text, and ``DecodeServer`` serves
prompt STRINGS to generated TEXT.

    python examples/text_serve_demo.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from kubetpu.jobs import (  # noqa: E402
    ModelConfig,
    init_state,
    make_mesh,
    make_train_step,
)
from kubetpu.jobs.serving import DecodeServer  # noqa: E402
from kubetpu.jobs.tokenizer import load_hf_tokenizer  # noqa: E402

SENTENCES = [
    "the quick brown fox jumps over the lazy dog.",
    "tpu kernels keep the mesh busy.",
]


def main():
    fixture = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests", "fixtures", "tiny_tokenizer.json",
    )
    tok = load_hf_tokenizer(fixture)
    print(f"tokenizer: vocab {tok.vocab_size}, bos={tok.bos_token!r}")

    cfg = ModelConfig(vocab=tok.vocab_size, d_model=64, n_layers=2,
                      n_heads=4, d_ff=128, max_seq=64)
    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1})
    state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh, optimizer=opt, use_ring=False)

    rows = [
        np.array(tok.encode(s, bos=True, eos=True), np.int32)
        for s in SENTENCES
    ]
    width = max(r.size for r in rows)
    batch = np.stack([np.pad(r, (0, width - r.size)) for r in rows] * 2)
    tokens, targets = batch[:, :-1], batch[:, 1:]
    for i in range(150):
        state, loss = step(state, tokens, targets)
    print(f"memorized {len(SENTENCES)} sentences (loss {float(loss):.4f})")

    server = DecodeServer(cfg, state.params, n_slots=2, max_seq=width + 8,
                          max_new_tokens=width, eos_id=tok.eos_id)
    prompts = ["the quick brown", "tpu kernels"]
    rids = [server.submit(tok.encode(p, bos=True)) for p in prompts]
    server.drain()
    for p, rid in zip(prompts, rids):
        text = tok.decode(server.pop_result(rid), skip_special=True)
        print(f"  {p!r} -> {text!r}")
    print("text serve demo OK")


if __name__ == "__main__":
    main()
