#!/usr/bin/env python3
"""Real text end to end: tokenize -> native corpus -> sharded train ->
evaluate -> generate.

A text file becomes a flat binary corpus (``ByteTokenizer.encode_file``),
the C++ mmap loader draws training windows from it, a sharded train step
runs on the virtual CPU mesh, ``evaluate`` reports validation loss +
perplexity, and the trained model generates a continuation that decodes
back to text. The same script is the multi-process recipe: each gang
worker passes its ``jax.process_index()`` to ``TokenFile.batches`` for a
disjoint corpus shard.

    python examples/text_demo.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

TEXT = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
    "how vexingly quick daft zebras jump. "
) * 40


def main() -> None:
    import jax

    # the environment may pin JAX to a hardware platform via sitecustomize;
    # this demo is a CPU-mesh walkthrough (same pattern as tests/conftest)
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from kubetpu.jobs import ModelConfig, init_state, make_eval_step, make_mesh, make_train_step
    from kubetpu.jobs.data import ByteTokenizer, evaluate, prefetch_to_mesh
    from kubetpu.jobs.decode import make_generate
    from kubetpu.jobs.native_data import TokenFile
    from kubetpu.jobs.train import make_optimizer

    work = tempfile.mkdtemp(prefix="kubetpu-text-")
    text_path = os.path.join(work, "corpus.txt")
    bin_path = os.path.join(work, "corpus.bin")
    with open(text_path, "w", encoding="utf-8") as f:
        f.write(TEXT)

    tok = ByteTokenizer()
    n = tok.encode_file(text_path, bin_path)
    print(f"tokenized {len(TEXT)} chars -> {n} tokens -> {bin_path}")

    cfg = ModelConfig(vocab=tok.vocab, d_model=64, n_layers=2, n_heads=4,
                      d_ff=128, max_seq=128)
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    opt = make_optimizer(lr=3e-3)
    state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh, optimizer=opt)
    step = make_train_step(cfg, mesh, optimizer=opt)

    with TokenFile(bin_path) as tf:
        train_batches = (b for _, b in zip(range(40), tf.batches(8, 32, seed=0)))
        for tokens, targets in prefetch_to_mesh(train_batches, mesh):
            state, loss = step(state, tokens, targets)
        print(f"trained {int(state.step)} steps, loss {float(loss):.3f}")

        r = evaluate(make_eval_step(cfg, mesh), state.params,
                     tf.batches(8, 32, seed=99), n_batches=4)
        print(f"validation: loss {r['loss']:.3f}, "
              f"perplexity {r['perplexity']:.1f} over {r['n_tokens']} tokens")

    prompt = tok.encode("the quick brown", bos=True, eos=False)
    out = make_generate(cfg)(
        state.params,
        np.asarray([prompt], np.int32),
        jax.random.PRNGKey(0),
        24,
    )
    completion = tok.decode(np.asarray(out)[0][len(prompt):])
    print(f"greedy continuation of 'the quick brown': {completion!r}")
    print("demo OK")


if __name__ == "__main__":
    main()
